"""Pallas (Mosaic) TPU kernels.

The reference accelerates its hot ops with hand-written CUDA/cuDNN
platform helpers dispatched before the generic implementation
(`include/ops/declarable/platform/cudnn/*.cu`, SURVEY §2.1). The
TPU-native analog: XLA already fuses almost everything; the few ops
that benefit from a hand-written kernel are implemented here with
Pallas and dispatched the same way — fast path when available,
generic jnp fallback otherwise.

Kernels:
- ``flash_attention`` — blockwise online-softmax attention
  (never materialises the [T,T] score matrix; VMEM-resident
  accumulators; MXU matmuls per block). Supports per-example key
  masks and dynamic global position offsets (for ring composition).
  Used by ``scaled_dot_attention`` for long sequences on TPU —
  including padded/masked batches — and composed per-KV-block by
  ``parallel.ring_attention`` over ICI (``flash_block_fwd`` /
  ``flash_block_bwd`` below are the composition surface: the ring
  carries (out, lse) accumulators between Pallas calls and merges
  them with exact log-sum-exp combination).
- ``threshold_encode`` / ``threshold_decode`` — fused gradient
  threshold compression (reference libnd4j ops ``encode_threshold`` /
  ``decode_threshold``): one VMEM pass computes the ternary
  quantisation, packs 16 two-bit codes per int32 word (16× smaller
  than f32), and emits the residual.

On CPU the kernels run in Pallas interpret mode (tests), so the same
code path is exercised everywhere.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu",)


def _vma(*xs) -> frozenset:
    """Union of the inputs' varying-manual-axes. Outside ``shard_map``
    this is empty; inside, ``pallas_call`` out_shapes must declare it
    (check_vma) — outputs vary over every axis an input varies over.
    Old jax (0.4.x) has neither ``jax.typeof`` nor vma tracking: there
    the union is always empty and the vma plumbing degrades to no-ops,
    which is exactly right — check_vma does not exist on that runtime."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    out: frozenset = frozenset()
    for x in xs:
        if x is not None:
            out = out | getattr(typeof(x), "vma", frozenset())
    return out


def _sds(shape, dtype, vma: frozenset):
    """``ShapeDtypeStruct`` carrying vma only when non-empty (the kwarg
    does not exist on old jax, where vma is always empty anyway)."""
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _align_vma(x, vma: frozenset):
    """Broadcast a replicated operand onto varying manual axes so every
    kernel operand carries the same vma (mixed vmas trip check_vma
    inside pallas interpret mode)."""
    if not vma:
        return x                    # incl. old jax: vma never tracked
    missing = vma - getattr(jax.typeof(x), "vma", frozenset())
    return lax.pcast(x, tuple(missing), to="varying") if missing else x


def _jnp_fallback(*xs) -> bool:
    """Pallas interpret mode (CPU) cannot run under shard_map manual
    axes (its internal index ops trip check_vma) — use the equivalent
    jnp path there. Real TPU lowering handles manual axes natively."""
    return _interpret() and bool(_vma(*xs))


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------
#
# All kernels take, in addition to q/k/v:
#  - km_ref: [1, 1, block_k] per-(batch·head) key validity mask block
#    (1 = attend, 0 = padded key; kernels read km_ref[0, 0]) — the
#    reference cuDNN fused-attention helper's mask operand analog;
#    blocks whose mask is all-zero are skipped entirely. The mask
#    rides as [BHkv, 1, Tk]: Mosaic requires a block's last two dims
#    be (8, 128)-divisible OR equal to the array dims, and the unit
#    sublane axis satisfies that at zero memory cost (a 2-D
#    [BHkv, Tk] operand with (1, block_k) blocks does NOT lower).
#  - off_ref: SMEM int32 [2] = (q_offset, k_offset) GLOBAL position
#    offsets used for causal masking. (0, 0) for single-device
#    attention; ring attention passes (my_idx·Tq, src_idx·Tk) so the
#    causal diagonal lands correctly on every ring step and blocks
#    fully above the diagonal are skipped without any work.


def _flash_kernel(q_ref, k_ref, v_ref, km_ref, off_ref, o_ref, *rest,
                  scale: float, causal: bool, t_real: int,
                  block_q: int, block_k: int):
    # rest = (lse_ref?, acc, m, l): the lse output only exists on the
    # differentiated path (inference pays no extra HBM writes)
    lse_ref = rest[0] if len(rest) == 4 else None
    acc, m, l = rest[-3:]
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        m[:] = jnp.full_like(m[:], -jnp.inf)
        l[:] = jnp.zeros_like(l[:])
        acc[:] = jnp.zeros_like(acc[:])

    # skip dead blocks entirely (the einsum path can't): kv blocks
    # fully past the real sequence, blocks whose key mask is all-zero,
    # and — causal — blocks fully above the (offset) diagonal
    i = pl.program_id(1)
    km = km_ref[0, 0]
    live = jnp.logical_and(j * block_k < t_real, jnp.any(km > 0))
    if causal:
        q_off, k_off = off_ref[0], off_ref[1]
        live = jnp.logical_and(
            live,
            k_off + j * block_k <= q_off + i * block_q + block_q - 1)

    @pl.when(live)
    def _():
        # operands stay in their storage dtype (bf16 in-model): the MXU
        # runs native bf16×bf16→f32; casting to f32 first would force
        # the multi-pass f32 matmul path at a fraction of peak. The
        # softmax scale folds into the q TILE ([bq, d] mul) instead of
        # the score tile ([bq, bk] mul — bk/d× more VPU work).
        qs = q_ref[0] * q_ref.dtype.type(scale)
        s = jnp.dot(qs, k_ref[0].T, preferred_element_type=jnp.float32)

        # mask padded kv positions (t_real is the unpadded length),
        # key-masked positions and (causal) above-diagonal entries by
        # folding -inf into s: exp(s - m) then yields exact zeros, so
        # no separate p-masking is needed. (A lax.cond that skips the
        # mask arithmetic on interior blocks was measured SLOWER on
        # v5e — the Mosaic branch costs more than the VPU ops saved.)
        kv_idx = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = jnp.logical_and(kv_idx < t_real,
                               jnp.broadcast_to(km[None, :] > 0,
                                                (block_q, block_k)))
        if causal:
            q_idx = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(
                mask, off_ref[1] + kv_idx <= off_ref[0] + q_idx)
        s = jnp.where(mask, s, -jnp.inf)

        m_prev = m[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        # exp(-inf - -inf) guard: rows with no live keys yet keep m=-inf
        p = jnp.exp(s - jnp.where(jnp.isinf(m_new), 0.0, m_new))
        alpha = jnp.exp(jnp.where(jnp.isinf(m_prev), -jnp.inf, m_prev)
                        - jnp.where(jnp.isinf(m_new), 0.0, m_new))
        alpha = jnp.where(jnp.isinf(m_prev), 0.0, alpha)

        l[:, :1] = l[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * alpha + jnp.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)
        m[:, :1] = m_new

    @pl.when(j == nk - 1)
    def _():
        den = jnp.maximum(l[:, :1], 1e-30)
        o_ref[0] = (acc[:] / den).astype(o_ref.dtype)
        if lse_ref is not None:
            # per-row logsumexp residual for the backward kernels
            # (FlashAttention-2: p = exp(s - lse) recomputed per
            # block); -inf for rows with no live keys
            lse_ref[0] = jnp.broadcast_to(m[:, :1] + jnp.log(den),
                                          lse_ref.shape[1:])


def _flash_blocks(tq_real: int, tk_real: int, d: int, block_q: int,
                  block_k: int):
    q128 = -(-tq_real // 128) * 128
    k128 = -(-tk_real // 128) * 128
    block_q = min(block_q, q128)              # don't block past the data
    block_k = min(block_k, k128)
    if not _interpret():
        # Mosaic: the km operand's LANE dim is block_k, which must be
        # a multiple of 128 (or span the whole padded array) — clamp
        # caller-tuned sub-128 block_k up on real hardware (interpret
        # mode keeps small blocks so CPU tests exercise multi-block
        # grids at small T)
        block_k = min(-(-block_k // 128) * 128, k128)
    tq = -(-tq_real // block_q) * block_q     # q and kv padded separately
    tk = -(-tk_real // block_k) * block_k     # (≤ one partial block each)
    dp = max(-(-d // 128) * 128, 128)         # lane-align head dim
    return block_q, block_k, tq, tk, dp


def _ones_km(x):
    return jnp.ones(x.shape[:2], jnp.float32)


def _zero_offs():
    return jnp.zeros((2,), jnp.int32)


def _expand_kv_rows(x, groups):
    """[B·Hkv, ...] → [B·H, ...] for the jnp fallback paths (rows are
    (batch, head)-major; query head h reads kv head h // groups)."""
    return x if (x is None or groups == 1) else \
        jnp.repeat(x, groups, axis=0)


def _reduce_kv_rows(dx, groups):
    """Transpose of :func:`_expand_kv_rows`: sum the per-query-head
    kv gradients onto their shared kv head."""
    if groups == 1:
        return dx
    bh = dx.shape[0]
    return jnp.sum(dx.reshape(bh // groups, groups, *dx.shape[1:]),
                   axis=1)


def _flash_fwd(q, k, v, km, offs, causal: bool, block_q: int,
               block_k: int, return_lse: bool = False,
               groups: int = 1):
    """q: [B·H, T, D] (heads folded); k,v: [B·H/groups, Tk, D] —
    grouped-query attention reads ONE kv block per head group straight
    from HBM via the BlockSpec index map (``b // groups``), never
    materialising the broadcast; km: [B·H/groups, Tk] key mask;
    offs: int32 [2] global (q, k) position offsets. Returns [BH, T, D]
    (and, for the vjp / ring composition, the per-row [BH, Tq, 1]
    logsumexp)."""
    if km is None:
        km = _ones_km(k)
    if offs is None:
        offs = _zero_offs()
    if _jnp_fallback(q, k, v):
        return _reference_scan(q, _expand_kv_rows(k, groups),
                               _expand_kv_rows(v, groups),
                               _expand_kv_rows(km, groups), offs,
                               causal, return_lse=return_lse)
    bh, t, d = q.shape
    if k.shape[0] * groups != bh:
        raise ValueError(f"kv rows ({k.shape[0]}) × groups ({groups}) "
                         f"!= q rows ({bh})")
    tk_real = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    block_q, block_k, tq, tk, dp = _flash_blocks(t, tk_real, d,
                                                 block_q, block_k)

    def pad(x, tpad):
        return jnp.pad(x, ((0, 0), (0, tpad - x.shape[1]),
                           (0, dp - d)))

    vma = _vma(q, k, v, km, offs)
    qp = _align_vma(pad(q, tq), vma)
    kp = _align_vma(pad(k, tk), vma)
    vp = _align_vma(pad(v, tk), vma)
    # km rides as [BHkv, 1, Tk]: Mosaic requires the block's last two
    # dims divisible by (8, 128) OR equal to the array dims — a unit
    # sublane axis satisfies that with zero memory overhead
    kmp = _align_vma(
        jnp.pad(km.astype(jnp.float32),
                ((0, 0), (0, tk - tk_real)))[:, None, :],
        vma)
    offs = _align_vma(offs.astype(jnp.int32), vma)
    nq, nk = tq // block_q, tk // block_k
    g = groups
    oshape = _sds((bh, tq, dp), q.dtype, vma)
    ospec = pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0))
    lshape = _sds((bh, tq, 128), jnp.float32, vma)
    lspec = pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0))
    res = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          t_real=tk_real, block_q=block_q,
                          block_k=block_k),
        out_shape=(oshape, lshape) if return_lse else oshape,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dp),
                         lambda b, i, j: (b // g, j, 0)),
            pl.BlockSpec((1, block_k, dp),
                         lambda b, i, j: (b // g, j, 0)),
            pl.BlockSpec((1, 1, block_k),
                         lambda b, i, j: (b // g, 0, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(ospec, lspec) if return_lse else ospec,
        scratch_shapes=[
            pltpu.VMEM((block_q, dp), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp, kmp, offs)
    if return_lse:
        out, lse = res
        # keep one lane per row as the residual (128x smaller);
        # _flash_bwd re-pads and re-broadcasts before its kernels
        return out[:, :t, :d], lse[:, :t, :1]
    return res[:, :t, :d]


def _reference_scan(q, k, v, km=None, offs=None, causal: bool = False,
                    block: int = 512, return_lse: bool = False):
    """Differentiable O(T)-memory blockwise attention in plain jnp
    (lax.scan over kv blocks) — the backward path and CPU fallback.
    Same mask/offset semantics as the Pallas kernel."""
    bh, t, d = q.shape
    tk_real = k.shape[1]
    tp = -(-tk_real // block) * block
    kp = jnp.pad(k, ((0, 0), (0, tp - tk_real), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, tp - tk_real), (0, 0)))
    kmp = (jnp.ones((bh, tp), jnp.float32) if km is None else
           jnp.pad(km.astype(jnp.float32),
                   ((0, 0), (0, tp - tk_real))))
    q_off = 0 if offs is None else offs[0]
    k_off = 0 if offs is None else offs[1]
    scale = 1.0 / (d ** 0.5)
    q_idx = q_off + jnp.arange(t)[:, None]

    def step(carry, blk):
        m_prev, l_prev, acc = carry
        kb, vb, kmb, j0 = blk
        s = jnp.einsum("bqd,bkd->bqk", q, kb) * scale
        kv_idx = j0 + jnp.arange(block)[None, :]
        mask = jnp.logical_and(kv_idx < tk_real, kmb[:, None, :] > 0)
        if causal:
            mask = jnp.logical_and(mask, k_off + kv_idx <= q_idx)
        s = jnp.where(mask, s, -jnp.inf)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        safe = jnp.where(jnp.isinf(m_new), 0.0, m_new)
        p = jnp.where(mask, jnp.exp(s - safe), 0.0)
        alpha = jnp.where(jnp.isinf(m_prev), 0.0,
                          jnp.exp(m_prev - safe))
        l_new = l_prev * alpha + jnp.sum(p, -1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bqk,bkd->bqd", p, vb)
        return (m_new, l_new, acc), None

    nb = tp // block
    kb = kp.reshape(bh, nb, block, d).swapaxes(0, 1)
    vb = vp.reshape(bh, nb, block, d).swapaxes(0, 1)
    kmb = kmp.reshape(bh, nb, block).swapaxes(0, 1)
    j0s = jnp.arange(nb) * block
    # under shard_map the carry must share the operands' varying axes
    vma = _vma(q, k, v, km, offs)
    init = tuple(_align_vma(x, vma) for x in (
        jnp.full((bh, t, 1), -jnp.inf),
        jnp.zeros((bh, t, 1)), jnp.zeros((bh, t, d))))
    (m, l, acc), _ = lax.scan(step, init, (kb, vb, kmb, j0s))
    den = jnp.maximum(l, 1e-30)
    out = (acc / den).astype(q.dtype)
    if return_lse:
        return out, (m + jnp.log(den)).astype(jnp.float32)
    return out


def _flash_bwd_masks(i, j, q_off, k_off, km, tq_real, tk_real, block_q,
                     block_k, causal):
    """(q,kv) validity mask for one [block_q, block_k] tile."""
    q_idx = i * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kv_idx = j * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = jnp.logical_and(q_idx < tq_real, kv_idx < tk_real)
    mask = jnp.logical_and(mask, jnp.broadcast_to(
        km[None, :] > 0, (block_q, block_k)))
    if causal:
        mask = jnp.logical_and(mask, k_off + kv_idx <= q_off + q_idx)
    return mask


def _flash_bwd_p_ds(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, i, j,
                    q_off, k_off, km, tq_real, tk_real, block_q,
                    block_k, causal, scale):
    """Recompute the probability tile and dS for the backward pass
    (FlashAttention-2 eq. dS = P ∘ (dP − Δ), Δ = rowsum(dO ∘ O)).
    Matmul operands stay in storage dtype (native bf16 MXU mode);
    softmax math and accumulation are f32. The softmax scale is
    folded into the q tile for s (and left OUT of dS — callers scale
    dq/dk once at write-out, saving a [bq, bk] multiply per pair);
    the mask folds into s as -inf so exp(s - lse) zeros masked
    entries with no separate p-masking pass. Returned q/k/do are the
    storage-dtype tiles; p/ds are f32 (cast to the operand dtype at
    their consuming matmuls, FA2-style)."""
    q, k, do = q_ref[0], k_ref[0], do_ref[0]
    qs = q * q_ref.dtype.type(scale)
    s = jnp.dot(qs, k.T, preferred_element_type=jnp.float32)
    mask = _flash_bwd_masks(i, j, q_off, k_off, km, tq_real,
                            tk_real, block_q, block_k, causal)
    s = jnp.where(mask, s, -jnp.inf)
    lse = lse_ref[0][:, :1]
    lse = jnp.where(jnp.isfinite(lse), lse, 0.0)
    p = jnp.exp(s - lse)
    delta = jnp.sum(do.astype(jnp.float32)
                    * o_ref[0].astype(jnp.float32), axis=-1,
                    keepdims=True)
    dp = jnp.dot(do, v_ref[0].T, preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    return q, k, do, p, ds


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                         km_ref, off_ref, dq_ref, acc, *, scale, causal,
                         tq_real, tk_real, block_q, block_k):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc[:])

    km = km_ref[0, 0]
    q_off, k_off = off_ref[0], off_ref[1]
    live = jnp.logical_and(j * block_k < tk_real, jnp.any(km > 0))
    if causal:
        live = jnp.logical_and(
            live,
            k_off + j * block_k <= q_off + i * block_q + block_q - 1)

    @pl.when(live)
    def _():
        _, k, _, _, ds = _flash_bwd_p_ds(
            q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, i, j, q_off,
            k_off, km, tq_real, tk_real, block_q, block_k, causal,
            scale)
        acc[:] += jnp.dot(ds.astype(k.dtype), k,
                          preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _():
        # dS carries no scale — applied once here ([bq, d] mul)
        dq_ref[0] = (acc[:] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                          km_ref, off_ref, dk_ref, dv_ref, acck, accv,
                          *, scale, causal, tq_real, tk_real, block_q,
                          block_k):
    j, i = pl.program_id(1), pl.program_id(2)   # kv outer, q inner
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _():
        acck[:] = jnp.zeros_like(acck[:])
        accv[:] = jnp.zeros_like(accv[:])

    km = km_ref[0, 0]
    q_off, k_off = off_ref[0], off_ref[1]
    live = jnp.logical_and(i * block_q < tq_real, jnp.any(km > 0))
    if causal:
        live = jnp.logical_and(
            live,
            q_off + i * block_q + block_q - 1 >= k_off + j * block_k)

    @pl.when(live)
    def _():
        q, _, do, p, ds = _flash_bwd_p_ds(
            q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, i, j, q_off,
            k_off, km, tq_real, tk_real, block_q, block_k, causal,
            scale)
        accv[:] += jnp.dot(p.astype(do.dtype).T, do,
                          preferred_element_type=jnp.float32)
        acck[:] += jnp.dot(ds.astype(q.dtype).T, q,
                          preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = (acck[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = accv[:].astype(dv_ref.dtype)


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                            km_ref, off_ref, dq_ref, dk_ref, dv_ref,
                            dq_acc, acck, accv, *, scale, causal,
                            tq_real, tk_real, block_q, block_k):
    """Single-pass FA2 backward: grid (bh, kv, q). Each (kv, q) block
    pair recomputes s/p/dS ONCE and feeds all three gradient matmuls
    (the split kernels recompute the pair twice — ~7 matmul-class ops
    per pair vs 5 here, and they stream q/k/v/do from HBM twice).
    dk/dv accumulate in per-kv-block VMEM scratch, written when the
    inner q sweep ends; dq accumulates in a full-length f32 VMEM
    scratch (contributions to q block i arrive once per OUTER kv step,
    so a per-block buffer can't persist) and streams the running
    partial to the output each step — the final kv iteration's flush
    is the converged value."""
    j, i = pl.program_id(1), pl.program_id(2)   # kv outer, q inner
    nq = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        # first visit of q block i this row: zero its dq scratch slice
        dq_acc[pl.ds(i * block_q, block_q)] = jnp.zeros(
            (block_q, dq_acc.shape[1]), jnp.float32)

    @pl.when(i == 0)
    def _():
        acck[:] = jnp.zeros_like(acck[:])
        accv[:] = jnp.zeros_like(accv[:])

    km = km_ref[0, 0]
    q_off, k_off = off_ref[0], off_ref[1]
    live = jnp.logical_and(
        jnp.logical_and(i * block_q < tq_real, j * block_k < tk_real),
        jnp.any(km > 0))
    if causal:
        live = jnp.logical_and(
            live,
            q_off + i * block_q + block_q - 1 >= k_off + j * block_k)

    @pl.when(live)
    def _():
        q, k, do, p, ds = _flash_bwd_p_ds(
            q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, i, j, q_off,
            k_off, km, tq_real, tk_real, block_q, block_k, causal,
            scale)
        accv[:] += jnp.dot(p.astype(do.dtype).T, do,
                           preferred_element_type=jnp.float32)
        acck[:] += jnp.dot(ds.astype(q.dtype).T, q,
                           preferred_element_type=jnp.float32)
        dq_acc[pl.ds(i * block_q, block_q)] += jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _():
        # dS carries no scale — applied once at write-out
        dk_ref[0] = (acck[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = accv[:].astype(dv_ref.dtype)

    dq_ref[0] = (dq_acc[pl.ds(i * block_q, block_q)]
                 * scale).astype(dq_ref.dtype)


# full-length dq scratch budget for the fused backward (f32 bytes).
# The kernel's total scoped VMEM is the dq scratch + dk/dv
# accumulators + double-buffered operand blocks (measured 17.1 MB at
# T=8192, bq=1024, bk=1024, dp=128), which exceeds Mosaic's 16 MB
# DEFAULT scoped-vmem limit — the fused call raises its
# vmem_limit_bytes to _FUSED_BWD_VMEM_LIMIT (physical VMEM on v5e is
# far larger). Past the scratch budget (T ≳ 24k at d≤128) fall back
# to the split kernels.
_FUSED_BWD_DQ_VMEM = 12 * 1024 * 1024
_FUSED_BWD_VMEM_LIMIT = 48 * 1024 * 1024


def _flash_bwd(q, k, v, out, lse, g, km, offs, causal, block_q,
               block_k, groups: int = 1):
    """Backward kernels. GQA (``groups`` > 1): kv operands stay at
    [B·Hkv] rows and are shared across each head group via the index
    map; dk/dv are produced per QUERY head (the accumulation grid runs
    per q head) and reduced onto the kv heads afterwards."""
    if _jnp_fallback(q, k, v, g):
        # shard_map manual axes on CPU: interpret-mode pallas can't run
        # there — exact jnp backward from the global lse instead
        dq, dk, dv = _reference_bwd_block(
            q, _expand_kv_rows(k, groups), _expand_kv_rows(v, groups),
            out, lse, g, _expand_kv_rows(km, groups), offs, causal)
        return (dq, _reduce_kv_rows(dk, groups),
                _reduce_kv_rows(dv, groups))
    if km is None:
        km = _ones_km(k)
    if offs is None:
        offs = _zero_offs()
    bh, t, d = q.shape
    if k.shape[0] * groups != bh:
        raise ValueError(f"kv rows ({k.shape[0]}) × groups ({groups}) "
                         f"!= q rows ({bh})")
    tk_real = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    block_q, block_k, tq, tk, dp = _flash_blocks(t, tk_real, d,
                                                 block_q, block_k)

    def pad(x, tpad):
        return jnp.pad(x, ((0, 0), (0, tpad - x.shape[1]),
                           (0, dp - d)))

    vma = _vma(q, k, v, g, km, offs)
    qp = _align_vma(pad(q, tq), vma)
    kp = _align_vma(pad(k, tk), vma)
    vp = _align_vma(pad(v, tk), vma)
    dop = _align_vma(pad(g, tq), vma)
    op = _align_vma(pad(out, tq), vma)
    kmp = _align_vma(
        jnp.pad(km.astype(jnp.float32),
                ((0, 0), (0, tk - tk_real)))[:, None, :],
        vma)
    offs = _align_vma(offs.astype(jnp.int32), vma)
    # residual is [BH, Tq, 1]; kernels read a full 128-lane block
    lsep = _align_vma(jnp.broadcast_to(
        jnp.pad(lse, ((0, 0), (0, tq - t), (0, 0))), (bh, tq, 128)),
        vma)
    nq, nk = tq // block_q, tk // block_k
    gg = groups
    kw = dict(scale=scale, causal=causal, tq_real=t, tk_real=tk_real,
              block_q=block_q, block_k=block_k)
    sspec = pl.BlockSpec(memory_space=pltpu.SMEM)
    # grid (bh, j, i): kv-side blocks follow grid axis 1, q axis 2;
    # dk/dv land per QUERY head and are group-reduced below
    qspec2 = pl.BlockSpec((1, block_q, dp), lambda b, y, x: (b, x, 0))
    lspec2 = pl.BlockSpec((1, block_q, 128), lambda b, y, x: (b, x, 0))
    kspec2 = pl.BlockSpec((1, block_k, dp),
                          lambda b, y, x: (b // gg, y, 0))
    kmspec2 = pl.BlockSpec((1, 1, block_k),
                           lambda b, y, x: (b // gg, 0, y))
    ospec2 = pl.BlockSpec((1, block_k, dp), lambda b, y, x: (b, y, 0))
    if tq * dp * 4 <= _FUSED_BWD_DQ_VMEM:
        dq, dk, dv = pl.pallas_call(
            functools.partial(_flash_bwd_fused_kernel, **kw),
            out_shape=(_sds((bh, tq, dp), q.dtype, vma),
                       _sds((bh, tk, dp), k.dtype, vma),
                       _sds((bh, tk, dp), v.dtype, vma)),
            grid=(bh, nk, nq),
            in_specs=[qspec2, kspec2, kspec2, qspec2, qspec2, lspec2,
                      kmspec2, sspec],
            out_specs=(qspec2, ospec2, ospec2),
            scratch_shapes=[pltpu.VMEM((tq, dp), jnp.float32),
                            pltpu.VMEM((block_k, dp), jnp.float32),
                            pltpu.VMEM((block_k, dp), jnp.float32)],
            compiler_params=None if _interpret() else
            pltpu.CompilerParams(
                vmem_limit_bytes=_FUSED_BWD_VMEM_LIMIT),
            interpret=_interpret(),
        )(qp, kp, vp, dop, op, lsep, kmp, offs)
        return (dq[:, :t, :d],
                _reduce_kv_rows(dk[:, :tk_real, :d], groups),
                _reduce_kv_rows(dv[:, :tk_real, :d], groups))
    # very long sequences: the full-length dq scratch would not fit in
    # VMEM — split dq / dkv passes with per-block accumulators
    qspec = pl.BlockSpec((1, block_q, dp), lambda b, x, y: (b, x, 0))
    lspec = pl.BlockSpec((1, block_q, 128), lambda b, x, y: (b, x, 0))
    kspec = pl.BlockSpec((1, block_k, dp),
                         lambda b, x, y: (b // gg, y, 0))
    kmspec = pl.BlockSpec((1, 1, block_k),
                          lambda b, x, y: (b // gg, 0, y))
    # grid (bh, i, j): q-side blocks follow grid axis 1, kv axis 2
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **kw),
        out_shape=_sds((bh, tq, dp), q.dtype, vma),
        grid=(bh, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, qspec, lspec, kmspec,
                  sspec],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((block_q, dp), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, op, lsep, kmp, offs)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **kw),
        out_shape=(_sds((bh, tk, dp), k.dtype, vma),
                   _sds((bh, tk, dp), v.dtype, vma)),
        grid=(bh, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, qspec2, lspec2,
                  kmspec2, sspec],
        out_specs=(ospec2, ospec2),
        scratch_shapes=[pltpu.VMEM((block_k, dp), jnp.float32),
                        pltpu.VMEM((block_k, dp), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, op, lsep, kmp, offs)
    return (dq[:, :t, :d],
            _reduce_kv_rows(dk[:, :tk_real, :d], groups),
            _reduce_kv_rows(dv[:, :tk_real, :d], groups))


def _reference_bwd_block(q, k, v, out, lse, g, km, offs, causal):
    """jnp backward for one (q-block, kv-block) pair given the global
    logsumexp — the interpret-mode/shard_map fallback of
    ``flash_block_bwd``. O(Tq·Tk) memory but only used on CPU tests."""
    bh, t, d = q.shape
    scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    q_idx = (0 if offs is None else offs[0]) + jnp.arange(t)[:, None]
    kv_idx = ((0 if offs is None else offs[1])
              + jnp.arange(k.shape[1])[None, :])
    mask = (jnp.ones(s.shape, bool) if km is None
            else jnp.broadcast_to(km[:, None, :] > 0, s.shape))
    if causal:
        mask = jnp.logical_and(mask, (kv_idx <= q_idx)[None])
    lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
    p = jnp.where(mask, jnp.exp(s - lse_safe), 0.0)
    gf = g.astype(jnp.float32)
    delta = jnp.sum(gf * out.astype(jnp.float32), -1, keepdims=True)
    dp = jnp.einsum("bqd,bkd->bqk", gf, v.astype(jnp.float32))
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bqk,bqd->bkd", ds, q.astype(jnp.float32))
    dv = jnp.einsum("bqk,bqd->bkd", p, gf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# --- ring composition surface ------------------------------------------------
def _ring_block_defaults(block_q, block_k, tk):
    """Measured v5e block policy shared with flash_attention: big q
    blocks; block_k 512 up to 4k keys, 1024 beyond."""
    if block_q is None:
        block_q = 1024
    if block_k is None:
        block_k = 512 if tk <= 4096 else 1024
    return block_q, block_k


def flash_block_fwd(q, k, v, km=None, offs=None, causal: bool = False,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    groups: int = 1):
    """One (local-Q × one-KV-block) flash forward returning
    ``(out, lse)`` — out is the softmax-normalised attention of q
    against ONLY this kv block, lse its per-row logsumexp. Two such
    partial results merge exactly via log-sum-exp combination
    (``ring_attention._merge_blocks``); the ring carries (out, lse)
    between Pallas calls. q: [B·H, T, D]; k,v: [B·H/groups, Tk, D]
    (GQA: the kernel shares one kv block per head group — no
    materialised broadcast); km: [B·H/groups, Tk]; offs: int32 [2]
    dynamic global (q, k) offsets for causal. Default blocks follow
    the measured v5e sweep — (1024, 512) up to 4k-key blocks (the
    usual ring regime; 1.44x vs the einsum pair at T/N=4096, see
    BASELINE.md), block_k 1024 beyond."""
    from deeplearning4j_tpu.obs import devtime
    block_q, block_k = _ring_block_defaults(block_q, block_k,
                                            k.shape[1])
    with devtime.scope("ops.flash_block_fwd"):
        return _flash_fwd(q, k, v, km, offs, causal, block_q, block_k,
                          return_lse=True, groups=groups)


def flash_block_bwd(q, k, v, out, lse, g, km=None, offs=None,
                    causal: bool = False,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None, groups: int = 1):
    """Backward of one (q-block, kv-block) pair given the GLOBAL
    (all-blocks) out/lse — FlashAttention-2 style recompute. Returns
    (dq_contrib, dk, dv): dq_contrib sums over kv blocks; dk/dv are
    this block's totals (at the KV head count when ``groups`` > 1)
    once every q block has contributed. (_flash_bwd itself falls back
    to the jnp backward under shard_map-on-CPU.)"""
    from deeplearning4j_tpu.obs import devtime
    block_q, block_k = _ring_block_defaults(block_q, block_k,
                                            k.shape[1])
    with devtime.scope("ops.flash_block_bwd"):
        return _flash_bwd(q, k, v, out, lse, g, km, offs, causal,
                          block_q, block_k, groups=groups)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, km, causal, block_q, block_k, groups=1, q_off=0):
    return _flash_fwd(q, k, v, km, _static_offs(q_off), causal,
                      block_q, block_k, groups=groups)


def _static_offs(q_off: int):
    return None if q_off == 0 else jnp.asarray([q_off, 0], jnp.int32)


def _flash_vjp_fwd(q, k, v, km, causal, block_q, block_k, groups,
                   q_off):
    out, lse = _flash_fwd(q, k, v, km, _static_offs(q_off), causal,
                          block_q, block_k, return_lse=True,
                          groups=groups)
    return out, (q, k, v, km, out, lse)


def _flash_vjp_bwd(causal, block_q, block_k, groups, q_off, res, g):
    q, k, v, km, out, lse = res
    dkm = None if km is None else jnp.zeros_like(km)
    return _flash_bwd(q, k, v, out, lse, g, km, _static_offs(q_off),
                      causal, block_q, block_k, groups=groups) + (dkm,)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    mask: Optional[jax.Array] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None):
    """Blockwise attention, [B, T, H, D] layout (head axis 2) like
    ``scaled_dot_attention``; ``mask``: optional [B, Tk] key mask.
    ``k``/``v`` may carry FEWER heads than ``q`` (grouped-query
    attention, H divisible by Hkv) — the kernels read the shared kv
    block per head group directly, no broadcast in HBM. Tq and Tk may
    differ (cross-attention / short-query-long-key); causal then masks
    against the END-ALIGNED diagonal (query row i attends keys
    ≤ i + Tk − Tq, matching the dense path's ``tril(..., Tk − Tq)``)
    — for valid rows: with Tq > Tk the leading Tq − Tk rows have NO
    live keys and the paths diverge there (kernel: zeros; einsum:
    uniform average), which is why ``_use_flash`` refuses causal
    Tq > Tk; mask such rows downstream if you call this directly.
    Differentiable: the backward is a pair of Pallas kernels (dQ;
    dK/dV) that recompute the probability tile per block from the
    saved logsumexp — FlashAttention-2 style, no [T,T] materialisation
    in either direction."""
    b, t, h, d = q.shape
    h_kv = k.shape[2]
    if h % h_kv:
        raise ValueError(f"q heads ({h}) not divisible by kv heads "
                         f"({h_kv})")
    # defaults from the v5e block sweep (tools/flash_crossover.py era,
    # causal fwd+bwd): big q blocks amortise the backward's kv-side
    # recompute — (1024, 512) wins ≤4k keys (−28% vs the old 256/1024
    # at T=2048), (1024, 1024) wins at 8k keys (−16%); larger q blocks
    # exceed VMEM at T=8k
    if block_q is None:
        block_q = 1024
    if block_k is None:
        block_k = 512 if k.shape[1] <= 4096 else 1024
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(
        b * x.shape[2], x.shape[1], -1)
    km = None
    if mask is not None:
        # per-example key mask → per-(batch·kv-head) rows
        km = jnp.repeat(mask.astype(jnp.float32), h_kv, axis=0)
    # devtime scope (ops/kernel_registry.py contract): the kernel's
    # own device time gets its own name in the gap report
    from deeplearning4j_tpu.obs import devtime
    with devtime.scope("ops.flash_attention"):
        o = _flash(fold(q), fold(k), fold(v), km, causal, block_q,
                   block_k, h // h_kv,
                   k.shape[1] - t if causal else 0)
    return o.reshape(b, h, t, d).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# threshold compression codec
# ---------------------------------------------------------------------------
_GROUP = 16          # 16 two-bit codes per int32 word
_BLOCK_COLS = 32768  # grid block width (16x32768 f32 = 2 MB VMEM)


def _encode_kernel(g_ref, tau_ref, packed_ref, resid_ref):
    tau = tau_ref[0]
    g = g_ref[:]                               # (16, C)
    code = jnp.where(g > tau, 1, jnp.where(g < -tau, 2, 0))
    q = jnp.where(g > tau, tau, jnp.where(g < -tau, -tau, 0.0))
    resid_ref[:] = g - q
    shifts = 2 * lax.broadcasted_iota(jnp.int32, g.shape, 0)
    packed_ref[:] = jnp.sum(code.astype(jnp.int32) << shifts, axis=0,
                            keepdims=True)


def _decode_kernel(p_ref, tau_ref, out_ref):
    tau = tau_ref[0]
    shifts = 2 * lax.broadcasted_iota(jnp.int32, out_ref.shape, 0)
    code = (p_ref[:] >> shifts) & 3            # broadcast (1,C)->(16,C)
    out_ref[:] = jnp.where(code == 1, tau,
                           jnp.where(code == 2, -tau, 0.0))


def _jnp_threshold_encode(g2, tau, size, shape):
    """jnp fallback of :func:`threshold_encode` over the padded
    ``(16, C)`` group layout — used under shard_map-on-CPU (interpret
    mode cannot run there) and declared in
    ``ops/kernel_registry.py``."""
    g2 = g2.astype(jnp.float32)
    tau_f = jnp.asarray(tau, jnp.float32)
    code = jnp.where(g2 > tau_f, 1, jnp.where(g2 < -tau_f, 2, 0))
    qv = jnp.where(g2 > tau_f, tau_f,
                   jnp.where(g2 < -tau_f, -tau_f, 0.0))
    shifts = 2 * jnp.arange(_GROUP, dtype=jnp.int32)[:, None]
    packed = jnp.sum(code.astype(jnp.int32) << shifts, axis=0,
                     keepdims=True)
    resid = g2 - qv
    residual = resid.T.reshape(-1)[:size].reshape(shape)
    return packed[0], residual


def threshold_encode(grad: jax.Array, tau):
    """Fused threshold encode: grad → (packed int32 codes, residual).

    Reference op ``encode_threshold`` (+ residual handling of
    ``EncodedGradientsAccumulator``): q = τ·sign(g)·1[|g|>τ]; 2 bits
    per element (code 0 / +τ=1 / −τ=2), residual = g − q.
    """
    from deeplearning4j_tpu.obs import devtime
    shape, size = grad.shape, grad.size
    flat = grad.reshape(-1)
    c = -(-size // _GROUP)
    c = -(-c // 128) * 128                     # lane-align columns
    flat = jnp.pad(flat, (0, _GROUP * c - size))
    g2 = flat.reshape(c, _GROUP).T             # (16, C), flat-major groups
    tau_arr = jnp.asarray([tau], jnp.float32)
    bc = min(c, _BLOCK_COLS)
    c = -(-c // bc) * bc
    g2 = jnp.pad(g2, ((0, 0), (0, c - g2.shape[1])))
    if _jnp_fallback(grad):
        return _jnp_threshold_encode(g2, tau, size, shape)
    tau_arr = _align_vma(tau_arr, _vma(grad))
    with devtime.scope("ops.threshold_encode"):
        packed, resid = pl.pallas_call(
            _encode_kernel,
            out_shape=(_sds((1, c), jnp.int32, _vma(grad)),
                       _sds((_GROUP, c), jnp.float32, _vma(grad))),
            grid=(c // bc,),
            in_specs=[pl.BlockSpec((_GROUP, bc), lambda i: (0, i)),
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=(pl.BlockSpec((1, bc), lambda i: (0, i)),
                       pl.BlockSpec((_GROUP, bc), lambda i: (0, i))),
            interpret=_interpret(),
        )(g2.astype(jnp.float32), tau_arr)
    residual = resid.T.reshape(-1)[:size].reshape(shape)
    return packed[0], residual


def _jnp_threshold_decode(packed, tau, size, shape):
    """jnp fallback of :func:`threshold_decode` (shard_map-on-CPU;
    declared in ``ops/kernel_registry.py``)."""
    tau_f = jnp.asarray(tau, jnp.float32)
    shifts = 2 * jnp.arange(_GROUP, dtype=jnp.int32)[:, None]
    code = (packed[None, :] >> shifts) & 3
    out = jnp.where(code == 1, tau_f,
                    jnp.where(code == 2, -tau_f, 0.0))
    dense = out.T.reshape(-1)[:size]
    return dense.reshape(shape) if shape is not None else dense


def threshold_decode(packed: jax.Array, tau, size: int, shape=None):
    """Reference op ``decode_threshold``: packed codes → dense ±τ."""
    from deeplearning4j_tpu.obs import devtime
    c0 = packed.shape[0]
    bc = min(c0, _BLOCK_COLS)
    c = -(-c0 // bc) * bc
    packed = jnp.pad(packed, (0, c - c0))
    if _jnp_fallback(packed):
        return _jnp_threshold_decode(packed, tau, size, shape)
    tau_arr = _align_vma(jnp.asarray([tau], jnp.float32), _vma(packed))
    with devtime.scope("ops.threshold_decode"):
        out = pl.pallas_call(
            _decode_kernel,
            out_shape=_sds((_GROUP, c), jnp.float32, _vma(packed)),
            grid=(c // bc,),
            in_specs=[pl.BlockSpec((1, bc), lambda i: (0, i)),
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=pl.BlockSpec((_GROUP, bc), lambda i: (0, i)),
            interpret=_interpret(),
        )(packed.reshape(1, c), tau_arr)
    dense = out.T.reshape(-1)[:size]
    return dense.reshape(shape) if shape is not None else dense
