"""The fused-primitive kernel registry — one table, three consumers.

Every PUBLIC Pallas kernel in ``ops/`` declares itself here with its
XLA fallback, its parity-test anchor, its ``devtime.scope`` name, and
the gap-report scopes it closes. The table is the contract that keeps
the kernel library honest:

- ``tools/lint_instrumentation.py`` **rule 9** parses this dict
  literal (AST, never imports the package) and enforces both
  directions: every public kernel function in ``ops/`` that reaches a
  ``pl.pallas_call`` has an entry (with a resolvable fallback, an
  existing parity test, and a scope site listed in ``SCOPE_SITES``),
  and every entry names a live kernel — plus the blanket rule that
  ``pl.pallas_call`` appears nowhere outside ``ops/``.
- ``obs/devtime.py`` ``gap_report()`` consults :func:`closed_by`:
  a ``pallas_candidate`` scope whose pattern a registered (and
  gate-active) kernel covers is reported CLOSED — the
  ``dl4j_tpu_devtime_scope_pallas_candidate`` gauge drops to 0 for it
  and the dossier's ``hot_path_gaps`` prints the closed/open split.
- ``tools/perf_dossier.py`` / ``bench.py`` iterate the table for the
  per-kernel parity/timing rows (``fused_epilogues`` /
  ``fused_kernels``).

``closes`` patterns are ``fnmatch`` globs over gap-report scope names.
Closure semantics: the scope's DOMINANT primitive (its attention or
normalisation math) now dispatches to the named kernel whenever the
kernel's platform gate is active — device time still reported under
the scope is the non-kernel remainder (projections, residual matmuls),
which is exactly what the dossier's closed/open split surfaces.
"""
from __future__ import annotations

import fnmatch
from typing import Any, Dict, Optional

#: kernel name -> declaration. PURE dict literal — lint rule 9 and the
#: dossier read it via AST without importing jax.
KERNEL_REGISTRY: Dict[str, Dict[str, Any]] = {
    "flash_attention": {
        "module": "ops/pallas_kernels.py",
        "fallback": "_reference_scan",
        "parity": "tests/test_pallas.py::test_flash_matches_reference",
        "scope": "ops.flash_attention",
        "closes": ("*.MultiHeadAttention", "*.SelfAttentionLayer",
                   "*.TransformerEncoderBlock",
                   "*.TransformerDecoderBlock", "prefill.block_*"),
        "gate": "flash",
    },
    "flash_block_fwd": {
        "module": "ops/pallas_kernels.py",
        "fallback": "_reference_scan",
        "parity": "tests/test_pallas.py::test_flash_block_offsets_compose",
        "scope": "ops.flash_block_fwd",
        "closes": (),          # ring composition surface — the ring
        "gate": "flash",       # callers own the end-to-end scopes
    },
    "flash_block_bwd": {
        "module": "ops/pallas_kernels.py",
        "fallback": "_reference_bwd_block",
        "parity": "tests/test_pallas.py::test_flash_block_bwd_composes",
        "scope": "ops.flash_block_bwd",
        "closes": (),
        "gate": "flash",
    },
    "threshold_encode": {
        "module": "ops/pallas_kernels.py",
        "fallback": "_jnp_threshold_encode",
        "parity": "tests/test_pallas.py::test_threshold_codec_roundtrip",
        "scope": "ops.threshold_encode",
        "closes": (),          # wire codec, not a layer epilogue
        "gate": "always",
    },
    "threshold_decode": {
        "module": "ops/pallas_kernels.py",
        "fallback": "_jnp_threshold_decode",
        "parity": "tests/test_pallas.py::test_threshold_codec_roundtrip",
        "scope": "ops.threshold_decode",
        "closes": (),
        "gate": "always",
    },
    "rms_norm": {
        "module": "ops/fused_norms.py",
        "fallback": "rms_norm_reference",
        "parity": "tests/test_fused_kernels.py::test_rms_norm_parity",
        "scope": "ops.rms_norm",
        # ONLY the scopes whose dominant primitive is the norm — the
        # decode/prefill block scopes also dispatch this kernel but
        # are matmul-dominated, and claiming them closed would hide
        # their remaining (real) pallas candidates forever
        "closes": ("*.RMSNorm",),
        "gate": "fused_norm",
    },
    "add_rms_norm": {
        "module": "ops/fused_norms.py",
        "fallback": "add_rms_norm_reference",
        "parity": "tests/test_fused_kernels.py::test_add_rms_norm_parity",
        "scope": "ops.add_rms_norm",
        "closes": (),          # rides inside *.TransformerDecoderBlock
        "gate": "fused_norm",  # (flash_attention already claims it)
    },
    "layer_norm": {
        "module": "ops/fused_norms.py",
        "fallback": "layer_norm_reference",
        "parity": "tests/test_fused_kernels.py::test_layer_norm_parity",
        "scope": "ops.layer_norm",
        "closes": ("*.LayerNormalization",),
        "gate": "fused_norm",
    },
}


def gate_active(gate: str) -> bool:
    """Is a kernel's dispatch gate live in the CURRENT environment?
    The per-shape thresholds (``DL4J_TPU_FLASH_MIN_T``,
    ``DL4J_TPU_FUSED_NORM_MIN_F``) are deliberately not modeled —
    closure is a platform-level statement ("this scope's primitive has
    a kernel and the platform dispatches it"), shape fallbacks keep
    working underneath it."""
    import jax

    from deeplearning4j_tpu.environment import get_flag
    if get_flag("DL4J_TPU_KERNEL_FORCE"):
        return True
    if gate == "always":
        return True
    return jax.default_backend() == "tpu"


def closed_by(scope: str) -> Optional[str]:
    """The registered kernel whose gate is active and whose ``closes``
    patterns cover ``scope`` — None when the gap is still open. The
    ``gap_report()`` consumer: a closed scope stops being a
    ``pallas_candidate`` and the dossier lists it under ``closed``."""
    for name, entry in KERNEL_REGISTRY.items():
        if any(fnmatch.fnmatchcase(scope, pat)
               for pat in entry["closes"]):
            if gate_active(entry["gate"]):
                return name
    return None
