"""Loss functions — reference: ``org.nd4j.linalg.lossfunctions.ILossFunction``
impls (~20; ``org.nd4j.linalg.lossfunctions.impl.LossMCXENT``, ``LossMSE``,
``LossBinaryXENT``, ``LossHinge``, …) and the ``LossFunctions.LossFunction``
enum.

API shape (functional, autodiff-friendly):
 - every loss is ``fn(labels, preds, mask=None, weights=None) -> scalar``
   (mean over batch, mask-weighted), where ``preds`` are *post-activation*
   outputs, mirroring ILossFunction.computeScore.
 - ``score_array(name, labels, preds, ...)`` gives per-example scores
   (ILossFunction.computeScoreArray) for evaluation/listeners.
 - gradients come from jax autodiff, not hand-written computeGradient.

Numerical-stability notes: cross-entropy losses offer ``from_logits`` so a
fused logsumexp path is used under jit (the reference instead pairs
LossMCXENT with a softmax activation and clips probabilities).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

_EPS = 1e-7


def _per_example(raw, mask):
    """Reduce feature axes to per-example scores, applying a mask.

    ``raw``: [batch, ...features] elementwise loss values.
    ``mask``: broadcastable to raw's leading axes (time-step masks in RNNs).
    """
    if mask is not None:
        m = jnp.reshape(mask, mask.shape + (1,) * (raw.ndim - mask.ndim))
        raw = raw * m
    axes = tuple(range(1, raw.ndim))
    return jnp.sum(raw, axis=axes) if axes else raw


def _mean(raw, mask):
    """Mean-over-batch of per-example (mask-weighted) summed scores.

    Reference semantics (BaseOutputLayer.computeScore): per-example score
    sums over features/timesteps (masked steps contribute 0); the batch
    score divides by minibatch size — so an all-ones mask is identical to
    no mask, and longer active sequences weigh more.
    """
    return jnp.mean(_per_example(raw, mask))


def _apply_weights(raw, weights):
    if weights is not None:
        raw = raw * jnp.asarray(weights, raw.dtype)
    return raw


# -- regression ------------------------------------------------------------

def mse(labels, preds, mask=None, weights=None):
    raw = _apply_weights(jnp.square(preds - labels), weights)
    return _mean(raw, mask)


def mae(labels, preds, mask=None, weights=None):
    raw = _apply_weights(jnp.abs(preds - labels), weights)
    return _mean(raw, mask)


l2 = mse
l1 = mae


def msle(labels, preds, mask=None, weights=None):
    raw = jnp.square(jnp.log1p(jnp.maximum(preds, -1 + _EPS))
                     - jnp.log1p(jnp.maximum(labels, -1 + _EPS)))
    return _mean(_apply_weights(raw, weights), mask)


def poisson(labels, preds, mask=None, weights=None):
    raw = preds - labels * jnp.log(jnp.maximum(preds, _EPS))
    return _mean(_apply_weights(raw, weights), mask)


def cosine_proximity(labels, preds, mask=None, weights=None):
    ln = labels / jnp.maximum(jnp.linalg.norm(labels, axis=-1,
                                              keepdims=True), _EPS)
    pn = preds / jnp.maximum(jnp.linalg.norm(preds, axis=-1,
                                             keepdims=True), _EPS)
    raw = -jnp.sum(_apply_weights(ln * pn, weights), axis=-1,
                   keepdims=True)
    return _mean(raw, mask)


# -- classification --------------------------------------------------------

def mcxent(labels, preds, mask=None, weights=None, from_logits=False):
    """Multi-class cross-entropy (reference LossMCXENT).

    ``labels`` one-hot (or soft). With ``from_logits`` the stable
    log_softmax path is used — preferred under jit on TPU.
    """
    if from_logits:
        logp = jax.nn.log_softmax(preds, axis=-1)
    else:
        logp = jnp.log(jnp.clip(preds, _EPS, 1.0))
    raw = _apply_weights(-labels * logp, weights)
    return _mean(raw, mask)


def sparse_mcxent(labels, preds, mask=None, weights=None, from_logits=False):
    """Integer-label cross-entropy (reference LossSparseMCXENT).

    The from-logits path is logsumexp-formulated: the [.., V] logits
    are read once (upcast per element inside the fused reduction — no
    f32 log-prob cube is ever materialised) and only the PICKED
    label logits are gathered. Accepts bf16 logits directly
    (``handles_low_precision_logits``): the logsumexp accumulates in
    f32, so a causal LM's [B, T, V] cube stays bf16 in HBM — worth
    ~3% of the train step at V=50k."""
    lab = labels.astype(jnp.int32)
    if from_logits:
        lse = jax.scipy.special.logsumexp(
            preds.astype(jnp.float32), axis=-1, keepdims=True)
        picked = jnp.take_along_axis(
            preds, lab[..., None], axis=-1).astype(jnp.float32)
        raw = lse - picked
    else:
        logp = jnp.log(jnp.clip(preds, _EPS, 1.0))
        raw = -jnp.take_along_axis(logp, lab[..., None], axis=-1)
    if weights is not None:
        raw = raw * jnp.take(jnp.asarray(weights, raw.dtype), lab)[..., None]
    return _mean(raw, mask)


sparse_mcxent.handles_low_precision_logits = True


def wants_f32_logits(fn, fused: bool) -> bool:
    """The single gate for the half-precision-training loss cast:
    losses that fold the upcast into their own reductions (marked
    ``handles_low_precision_logits``) take fused logits in the compute
    dtype directly — the [.., V] cube never round-trips HBM in f32.
    Everything else (and every non-fused path) gets f32 preds."""
    return not (fused and getattr(fn, "handles_low_precision_logits",
                                  False))


negativeloglikelihood = mcxent


def binary_xent(labels, preds, mask=None, weights=None, from_logits=False):
    """Binary cross-entropy (reference LossBinaryXENT / XENT)."""
    if from_logits:
        # log-sigmoid formulation: max(x,0) - x*z + log1p(exp(-|x|))
        x = preds
        raw = jnp.maximum(x, 0) - x * labels + jnp.log1p(jnp.exp(-jnp.abs(x)))
    else:
        p = jnp.clip(preds, _EPS, 1.0 - _EPS)
        raw = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p))
    return _mean(_apply_weights(raw, weights), mask)


def hinge(labels, preds, mask=None, weights=None):
    """labels in {-1, +1} or {0,1} (converted) — reference LossHinge."""
    y = jnp.where(labels > 0, 1.0, -1.0)
    raw = jnp.maximum(0.0, 1.0 - y * preds)
    return _mean(_apply_weights(raw, weights), mask)


def squared_hinge(labels, preds, mask=None, weights=None):
    y = jnp.where(labels > 0, 1.0, -1.0)
    raw = jnp.square(jnp.maximum(0.0, 1.0 - y * preds))
    return _mean(_apply_weights(raw, weights), mask)


def kl_divergence(labels, preds, mask=None, weights=None):
    p = jnp.clip(labels, _EPS, 1.0)
    q = jnp.clip(preds, _EPS, 1.0)
    raw = p * (jnp.log(p) - jnp.log(q))
    return _mean(_apply_weights(raw, weights), mask)


def wasserstein(labels, preds, mask=None, weights=None):
    return _mean(_apply_weights(labels * preds, weights), mask)


def huber(labels, preds, mask=None, weights=None, delta: float = 1.0):
    d = jnp.abs(preds - labels)
    raw = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return _mean(_apply_weights(raw, weights), mask)


def logcosh(labels, preds, mask=None, weights=None):
    d = preds - labels
    # numerically stable log(cosh(d)) = d + softplus(-2d) - log 2
    raw = d + jax.nn.softplus(-2.0 * d) - jnp.log(2.0)
    return _mean(_apply_weights(raw, weights), mask)


def fmeasure(labels, preds, mask=None, weights=None, beta: float = 1.0):
    """Differentiable F-beta surrogate (reference LossFMeasure, binary)."""
    w = jnp.ones_like(preds)
    if weights is not None:
        w = w * jnp.asarray(weights, preds.dtype)
    if mask is not None:
        m = jnp.reshape(mask, mask.shape + (1,) * (preds.ndim - mask.ndim))
        w = w * m
    tp = jnp.sum(w * labels * preds)
    fp = jnp.sum(w * (1 - labels) * preds)
    fn = jnp.sum(w * labels * (1 - preds))
    b2 = beta * beta
    f = ((1 + b2) * tp) / jnp.maximum((1 + b2) * tp + b2 * fn + fp, _EPS)
    return 1.0 - f


def ctc_loss(labels, logits, label_lengths, logit_lengths, blank_id: int = 0):
    """CTC loss (reference libnd4j ``ctc_loss`` declarable op).

    logits: [B, T, C] unnormalized; labels: [B, S] int32 padded.
    Uses optax's CTC implementation (forward-backward in log space via
    lax.scan — jit/TPU friendly).
    """
    import optax
    logit_pad = (jnp.arange(logits.shape[1])[None, :]
                 >= logit_lengths[:, None]).astype(logits.dtype)
    label_pad = (jnp.arange(labels.shape[1])[None, :]
                 >= label_lengths[:, None]).astype(logits.dtype)
    per = optax.ctc_loss(logits, logit_pad, labels.astype(jnp.int32),
                         label_pad, blank_id=blank_id)
    return jnp.mean(per)


_REGISTRY: Dict[str, Callable] = {
    "mse": mse,
    "l2": l2,
    "mae": mae,
    "l1": l1,
    "msle": msle,
    "mean_squared_logarithmic_error": msle,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "sparse_mcxent": sparse_mcxent,
    "xent": binary_xent,
    "binary_xent": binary_xent,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "kl_divergence": kl_divergence,
    "kld": kl_divergence,
    "huber": huber,
    "logcosh": logcosh,
    "reconstruction_crossentropy": binary_xent,
    "wasserstein": wasserstein,
    "fmeasure": fmeasure,
}


def get(name_or_fn) -> Callable:
    """Resolve a loss by name. A ``name:param`` suffix parametrizes
    losses with a scalar knob (``"huber:2.0"`` → delta,
    ``"fmeasure:2.0"`` → beta); serializable in layer configs."""
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if ":" in key:
        base, _, arg = key.partition(":")
        val = float(arg)
        if base == "huber":
            return lambda l, p, mask=None, weights=None: \
                huber(l, p, mask, weights, delta=val)
        if base == "fmeasure":
            return lambda l, p, mask=None, weights=None: \
                fmeasure(l, p, mask, weights, beta=val)
        raise ValueError(f"loss {base!r} takes no parameter")
    if key not in _REGISTRY:
        raise ValueError(f"Unknown loss {name_or_fn!r}; known: "
                         f"{sorted(_REGISTRY)}")
    return _REGISTRY[key]


def score_array(name_or_fn, labels, preds, mask=None, weights=None,
                **kw):
    """Per-example scores (reference ILossFunction.computeScoreArray)."""
    fn = get(name_or_fn)
    # Recompute elementwise raw values by vmapping the scalar loss over
    # the batch axis.
    def one(l, p, m):
        return fn(l[None], p[None], None if m is None else m[None],
                  weights, **kw)
    if mask is None:
        return jax.vmap(lambda l, p: fn(l[None], p[None], None,
                                        weights, **kw))(labels, preds)
    return jax.vmap(one)(labels, preds, mask)


def names():
    return sorted(_REGISTRY)
