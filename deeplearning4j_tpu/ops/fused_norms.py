"""Fused norm / residual epilogue kernels (Pallas, fwd + bwd).

The round-5 device-time observatory (``obs/devtime.py``,
``gap_report()``) named the normalisation epilogues as
``pallas_candidate`` scopes: every RMSNorm/LayerNorm in the layer
stack lowers to a chain of small VPU ops (square, reduce, rsqrt,
broadcast-multiply) that XLA schedules as separate passes over the
activation — low roofline utilization on a tensor the adjacent matmul
already streamed through VMEM. The cuDNN-primitives shape of the win
(PAPERS.md: arxiv 1410.0759): a SMALL library of tuned fused
primitives behind the existing layer API, dispatched platform-helper
style (``nn/layers/attention.py::_use_flash`` is the pattern).

Kernels (each: one VMEM pass fwd, one recompute-style pass bwd, the
cross-row ``dgamma``/``dbeta`` reductions accumulated across the
sequential TPU grid):

- :func:`rms_norm` — RMSNorm over the trailing axis. Dispatched from
  ``nn.layers.core.RMSNorm`` and ``zoo.gpt._rms`` (train blocks AND
  the KV-cached decode/prefill paths).
- :func:`add_rms_norm` — residual add + RMSNorm in one pass,
  returning ``(normed, summed)`` — the pre-norm transformer block's
  ``x = x + attn; h = rms(x)`` epilogue
  (``nn.layers.attention.TransformerDecoderBlock``).
- :func:`layer_norm` — LayerNorm (mean subtraction + bias) over the
  trailing axis, dispatched from ``nn.layers.core.LayerNormalization``
  (and through it the encoder block stack).

Dispatch contract (ARCHITECTURE.md §17): the gate decides at TRACE
time. Gate OFF returns the *exact* jnp expression the layers used
before this module existed — same ops in the same order, so the
compiled program is byte-identical (fenced in
tests/test_fused_kernels.py). Gate ON requires a TPU backend — or
``DL4J_TPU_KERNEL_FORCE=1``, which forces the kernel path in Pallas
interpret mode so CPU CI exercises the dispatch decision itself (the
``environment.py`` flag the testability satellite of ISSUE 15 added).
Every kernel's device time lands under its own ``devtime.scope``
(``ops.rms_norm`` / ``ops.add_rms_norm`` / ``ops.layer_norm``) and is
declared in ``ops/kernel_registry.py`` with its fallback + parity
test, which is how ``gap_report()`` marks the norm scopes ``closed``.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from deeplearning4j_tpu.ops.pallas_kernels import (_interpret,
                                                   _jnp_fallback)

#: default trailing-axis epsilon — numerically the same constant as
#: ``nn.layers.core.RMSNORM_EPS`` (kept literal here: the layer stack
#: imports THIS module, so importing the layer constant back would
#: cycle); callers always pass their layer's eps explicitly.
RMSNORM_EPS = 1e-6
LAYERNORM_EPS = 1e-5

#: VMEM budget per operand block (bytes of f32): bounds block_rows at
#: large feature dims so the row block + its f32 upcast stay resident
_BLOCK_BYTES = 2 * 1024 * 1024


def _use_fused(x, *params) -> bool:
    """The dispatch gate, decided at trace time. TPU dispatches the
    kernel (features ≥ ``DL4J_TPU_FUSED_NORM_MIN_F`` — tiny rows would
    pad to a full 128-lane block for no bandwidth win); CPU/old-jaxlib
    falls back to the XLA expression value-for-value;
    ``DL4J_TPU_KERNEL_FORCE`` forces the kernel in interpret mode so
    CI covers the dispatch decision. float64 (gradient checking) and
    shard_map-manual-axes-on-CPU (interpret can't run there — the
    flash kernels' rule) always fall back."""
    from deeplearning4j_tpu.environment import get_flag
    if x.ndim < 2 or x.dtype == jnp.float64:
        return False
    if _jnp_fallback(x, *params):
        return False
    if get_flag("DL4J_TPU_KERNEL_FORCE"):
        return True
    return (jax.default_backend() == "tpu"
            and x.shape[-1] >= get_flag("DL4J_TPU_FUSED_NORM_MIN_F"))


def _blocks(r: int, f: int) -> Tuple[int, int, int]:
    """(block_rows, padded_rows, padded_features): features lane-align
    to 128, rows sublane-align to 8, block_rows bounded by the VMEM
    budget (Mosaic wants the last two block dims (8, 128)-divisible or
    equal to the array dims)."""
    fp = max(128, -(-f // 128) * 128)
    br = max(8, min(256, (_BLOCK_BYTES // (fp * 4)) // 8 * 8))
    br = min(br, -(-r // 8) * 8)
    rp = -(-r // br) * br
    return br, rp, fp


def _pad2(x, rp: int, fp: int):
    return jnp.pad(x, ((0, rp - x.shape[0]), (0, fp - x.shape[1])))


def _pad_vec(v, fp: int):
    return jnp.pad(v, (0, fp - v.shape[0])).reshape(1, fp)


# ---------------------------------------------------------------------------
# RMSNorm
# ---------------------------------------------------------------------------

def _rms_fwd_kernel(x_ref, g_ref, o_ref, *, eps: float, f_real: int):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.sum(x * x, axis=-1, keepdims=True) / f_real
    rstd = lax.rsqrt(ms + eps)
    o_ref[...] = (x * rstd
                  * g_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_bwd_kernel(x_ref, g_ref, do_ref, dx_ref, dg_ref, *,
                    eps: float, f_real: int):
    # dgamma accumulates across the sequential row-block grid; the
    # recompute of rstd from the x block (FlashAttention-style) saves
    # writing/reading a per-row residual through HBM
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dg_ref[...] = jnp.zeros_like(dg_ref)

    x = x_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    gam = g_ref[...].astype(jnp.float32)
    ms = jnp.sum(x * x, axis=-1, keepdims=True) / f_real
    rstd = lax.rsqrt(ms + eps)
    gg = do * gam
    c = jnp.sum(gg * x, axis=-1, keepdims=True) / f_real
    dx_ref[...] = ((gg - x * (c * rstd * rstd)) * rstd).astype(
        dx_ref.dtype)
    dg_ref[...] += jnp.sum(do * x * rstd, axis=0, keepdims=True)


def _rms_fwd_call(x2, gamma, eps: float):
    r, f = x2.shape
    br, rp, fp = _blocks(r, f)
    out = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps, f_real=f),
        out_shape=jax.ShapeDtypeStruct((rp, fp), x2.dtype),
        grid=(rp // br,),
        in_specs=[pl.BlockSpec((br, fp), lambda i: (i, 0)),
                  pl.BlockSpec((1, fp), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((br, fp), lambda i: (i, 0)),
        interpret=_interpret(),
    )(_pad2(x2, rp, fp), _pad_vec(gamma, fp))
    return out[:r, :f]


def _rms_bwd_call(x2, gamma, do2, eps: float):
    r, f = x2.shape
    br, rp, fp = _blocks(r, f)
    dx, dg = pl.pallas_call(
        functools.partial(_rms_bwd_kernel, eps=eps, f_real=f),
        out_shape=(jax.ShapeDtypeStruct((rp, fp), x2.dtype),
                   jax.ShapeDtypeStruct((1, fp), jnp.float32)),
        grid=(rp // br,),
        in_specs=[pl.BlockSpec((br, fp), lambda i: (i, 0)),
                  pl.BlockSpec((1, fp), lambda i: (0, 0)),
                  pl.BlockSpec((br, fp), lambda i: (i, 0))],
        out_specs=(pl.BlockSpec((br, fp), lambda i: (i, 0)),
                   pl.BlockSpec((1, fp), lambda i: (0, 0))),
        interpret=_interpret(),
    )(_pad2(x2, rp, fp), _pad_vec(gamma, fp), _pad2(do2, rp, fp))
    return dx[:r, :f], dg[0, :f].astype(gamma.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms(x2, gamma, eps):
    return _rms_fwd_call(x2, gamma, eps)


def _rms_vjp_fwd(x2, gamma, eps):
    return _rms_fwd_call(x2, gamma, eps), (x2, gamma)


def _rms_vjp_bwd(eps, res, g):
    x2, gamma = res
    return _rms_bwd_call(x2, gamma, g, eps)


_rms.defvjp(_rms_vjp_fwd, _rms_vjp_bwd)


def rms_norm_reference(x, gamma, eps: float = RMSNORM_EPS):
    """The XLA fallback — EXACTLY the expression
    ``nn.layers.core.RMSNorm`` / ``zoo.gpt._rms`` used before this
    module existed (same ops, same order: the gate-off program is
    byte-identical, fenced in tests/test_fused_kernels.py)."""
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * lax.rsqrt(ms + eps) * gamma


def rms_norm(x, gamma, eps: float = RMSNORM_EPS):
    """RMSNorm over the trailing axis, platform-helper dispatched:
    Pallas fused fwd+bwd on TPU (or under ``DL4J_TPU_KERNEL_FORCE``
    in interpret mode), :func:`rms_norm_reference` everywhere else."""
    if not _use_fused(x, gamma):
        return rms_norm_reference(x, gamma, eps)
    from deeplearning4j_tpu.obs import devtime
    with devtime.scope("ops.rms_norm"):
        shape = x.shape
        y = _rms(x.reshape(-1, shape[-1]), gamma, float(eps))
        return y.reshape(shape)


# ---------------------------------------------------------------------------
# residual add + RMSNorm (the pre-norm block epilogue)
# ---------------------------------------------------------------------------

def _add_rms_fwd_kernel(x_ref, d_ref, g_ref, o_ref, s_ref, *,
                        eps: float, f_real: int):
    s = x_ref[...].astype(jnp.float32) + d_ref[...].astype(jnp.float32)
    s_ref[...] = s.astype(s_ref.dtype)
    ms = jnp.sum(s * s, axis=-1, keepdims=True) / f_real
    rstd = lax.rsqrt(ms + eps)
    o_ref[...] = (s * rstd
                  * g_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _add_rms_fwd_call(x2, d2, gamma, eps: float):
    r, f = x2.shape
    br, rp, fp = _blocks(r, f)
    y, s = pl.pallas_call(
        functools.partial(_add_rms_fwd_kernel, eps=eps, f_real=f),
        out_shape=(jax.ShapeDtypeStruct((rp, fp), x2.dtype),
                   jax.ShapeDtypeStruct((rp, fp), x2.dtype)),
        grid=(rp // br,),
        in_specs=[pl.BlockSpec((br, fp), lambda i: (i, 0)),
                  pl.BlockSpec((br, fp), lambda i: (i, 0)),
                  pl.BlockSpec((1, fp), lambda i: (0, 0))],
        out_specs=(pl.BlockSpec((br, fp), lambda i: (i, 0)),
                   pl.BlockSpec((br, fp), lambda i: (i, 0))),
        interpret=_interpret(),
    )(_pad2(x2, rp, fp), _pad2(d2, rp, fp), _pad_vec(gamma, fp))
    return y[:r, :f], s[:r, :f]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _add_rms(x2, d2, gamma, eps):
    return _add_rms_fwd_call(x2, d2, gamma, eps)


def _add_rms_vjp_fwd(x2, d2, gamma, eps):
    y, s = _add_rms_fwd_call(x2, d2, gamma, eps)
    return (y, s), (s, gamma)


def _add_rms_vjp_bwd(eps, res, ct):
    # d(x + delta) is shared: the norm's dx (recomputed from the saved
    # sum via the rms bwd kernel) plus the residual stream's own
    # cotangent flows identically into both addends
    s, gamma = res
    dy, ds = ct
    dxs, dg = _rms_bwd_call(s, gamma, dy, eps)
    dtot = dxs + ds.astype(dxs.dtype)
    return dtot, dtot, dg


_add_rms.defvjp(_add_rms_vjp_fwd, _add_rms_vjp_bwd)


def add_rms_norm_reference(x, delta, gamma, eps: float = RMSNORM_EPS):
    """Fallback: the unfused residual-then-norm pair, exactly as the
    pre-norm decoder block wrote it (``x = x + delta`` then the
    :func:`rms_norm_reference` expression)."""
    s = x + delta
    return rms_norm_reference(s, gamma, eps), s


def add_rms_norm(x, delta, gamma, eps: float = RMSNORM_EPS):
    """Residual add + RMSNorm in ONE pass: returns ``(normed,
    summed)`` where ``summed = x + delta`` feeds the block's next
    residual. Fused, the activation streams through VMEM once instead
    of (add write) + (norm read) + (norm write)."""
    if not _use_fused(x, gamma, delta):
        return add_rms_norm_reference(x, delta, gamma, eps)
    from deeplearning4j_tpu.obs import devtime
    with devtime.scope("ops.add_rms_norm"):
        shape = x.shape
        y, s = _add_rms(x.reshape(-1, shape[-1]),
                        delta.reshape(-1, shape[-1]), gamma,
                        float(eps))
        return y.reshape(shape), s.reshape(shape)


# ---------------------------------------------------------------------------
# LayerNorm
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, g_ref, b_ref, o_ref, *, eps: float,
                   f_real: int):
    x = x_ref[...].astype(jnp.float32)
    # padded lanes carry zeros, which would bias the centered moments —
    # mask them out of xc so mean/var divide by the REAL feature count
    colmask = lax.broadcasted_iota(jnp.int32, x.shape, 1) < f_real
    mu = jnp.sum(x, axis=-1, keepdims=True) / f_real
    xc = jnp.where(colmask, x - mu, 0.0)
    var = jnp.sum(xc * xc, axis=-1, keepdims=True) / f_real
    y = xc / jnp.sqrt(var + eps)
    o_ref[...] = (y * g_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_bwd_kernel(x_ref, g_ref, do_ref, dx_ref, dg_ref, db_ref, *,
                   eps: float, f_real: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dg_ref[...] = jnp.zeros_like(dg_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    x = x_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    gam = g_ref[...].astype(jnp.float32)
    colmask = lax.broadcasted_iota(jnp.int32, x.shape, 1) < f_real
    mu = jnp.sum(x, axis=-1, keepdims=True) / f_real
    xc = jnp.where(colmask, x - mu, 0.0)
    var = jnp.sum(xc * xc, axis=-1, keepdims=True) / f_real
    rstd = lax.rsqrt(var + eps)
    xhat = xc * rstd
    gh = do * gam                  # zero on padded lanes (gamma pads 0)
    m1 = jnp.sum(gh, axis=-1, keepdims=True) / f_real
    m2 = jnp.sum(gh * xhat, axis=-1, keepdims=True) / f_real
    dx = (gh - m1 - xhat * m2) * rstd
    dx_ref[...] = jnp.where(colmask, dx, 0.0).astype(dx_ref.dtype)
    dg_ref[...] += jnp.sum(do * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(do, axis=0, keepdims=True)


def _ln_fwd_call(x2, gamma, beta, eps: float):
    r, f = x2.shape
    br, rp, fp = _blocks(r, f)
    out = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps, f_real=f),
        out_shape=jax.ShapeDtypeStruct((rp, fp), x2.dtype),
        grid=(rp // br,),
        in_specs=[pl.BlockSpec((br, fp), lambda i: (i, 0)),
                  pl.BlockSpec((1, fp), lambda i: (0, 0)),
                  pl.BlockSpec((1, fp), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((br, fp), lambda i: (i, 0)),
        interpret=_interpret(),
    )(_pad2(x2, rp, fp), _pad_vec(gamma, fp), _pad_vec(beta, fp))
    return out[:r, :f]


def _ln_bwd_call(x2, gamma, do2, eps: float):
    r, f = x2.shape
    br, rp, fp = _blocks(r, f)
    dx, dg, db = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps=eps, f_real=f),
        out_shape=(jax.ShapeDtypeStruct((rp, fp), x2.dtype),
                   jax.ShapeDtypeStruct((1, fp), jnp.float32),
                   jax.ShapeDtypeStruct((1, fp), jnp.float32)),
        grid=(rp // br,),
        in_specs=[pl.BlockSpec((br, fp), lambda i: (i, 0)),
                  pl.BlockSpec((1, fp), lambda i: (0, 0)),
                  pl.BlockSpec((br, fp), lambda i: (i, 0))],
        out_specs=(pl.BlockSpec((br, fp), lambda i: (i, 0)),
                   pl.BlockSpec((1, fp), lambda i: (0, 0)),
                   pl.BlockSpec((1, fp), lambda i: (0, 0))),
        interpret=_interpret(),
    )(_pad2(x2, rp, fp), _pad_vec(gamma, fp), _pad2(do2, rp, fp))
    return (dx[:r, :f], dg[0, :f].astype(gamma.dtype),
            db[0, :f].astype(gamma.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln(x2, gamma, beta, eps):
    return _ln_fwd_call(x2, gamma, beta, eps)


def _ln_vjp_fwd(x2, gamma, beta, eps):
    return _ln_fwd_call(x2, gamma, beta, eps), (x2, gamma)


def _ln_vjp_bwd(eps, res, g):
    x2, gamma = res
    return _ln_bwd_call(x2, gamma, g, eps)


_ln.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def layer_norm_reference(x, gamma, beta, eps: float = LAYERNORM_EPS):
    """The XLA fallback — EXACTLY
    ``nn.layers.core.LayerNormalization``'s pre-existing expression
    (same ops, same order: gate-off programs are byte-identical)."""
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) / jnp.sqrt(var + eps)
    return y * gamma + beta


def layer_norm(x, gamma, beta, eps: float = LAYERNORM_EPS):
    """LayerNorm over the trailing axis, platform-helper dispatched
    like :func:`rms_norm` (fused single-pass moments + normalisation;
    bwd recomputes the moments per block and accumulates
    dgamma/dbeta across the row grid)."""
    if not _use_fused(x, gamma, beta):
        return layer_norm_reference(x, gamma, beta, eps)
    from deeplearning4j_tpu.obs import devtime
    with devtime.scope("ops.layer_norm"):
        shape = x.shape
        y = _ln(x.reshape(-1, shape[-1]), gamma, beta, float(eps))
        return y.reshape(shape)


# ---------------------------------------------------------------------------
# bench row (bench.py `fused_kernels` / dossier `fused_epilogues`)
# ---------------------------------------------------------------------------

def fused_kernels_report(rows: int = 2048, feats: int = 512,
                         iters: int = 30):
    """Per-kernel interpret-parity status + fallback timings — the
    ``fused_kernels`` section of ``bench.py`` and the dossier's
    ``fused_epilogues`` entry. On CPU the kernel timings are interpret
    mode (wiring validation, labeled); the parity numbers are the real
    contract — the same kernel code lowers through Mosaic on TPU."""
    import os
    import time

    import numpy as np

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((rows, feats)), jnp.float32)
    d = jnp.asarray(rng.standard_normal((rows, feats)), jnp.float32)
    gam = jnp.asarray(rng.standard_normal((feats,)), jnp.float32)
    bet = jnp.asarray(rng.standard_normal((feats,)), jnp.float32)
    co = jnp.asarray(rng.standard_normal((rows, feats)), jnp.float32)

    def timed(fn, *args):
        # operands are jit ARGUMENTS — closed-over constants would
        # let XLA constant-fold part of the program (measured 2.4x
        # skew on the reference norm) and invalidate the
        # kernel-vs-fallback comparison this row exists for
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3

    def first(t):
        return jax.tree_util.tree_leaves(t)[0]

    # the baseline pass calls the *_reference fallbacks DIRECTLY —
    # toggling the env gate cannot force the fallback on a real TPU
    # (the platform gate dispatches the kernel regardless), and a
    # kernel-vs-kernel comparison would certify parity that was never
    # measured. EVERY operand (incl. the residual delta and beta)
    # rides as a jit argument so neither arm's program constant-folds.
    cases = {
        "rms_norm": (
            rms_norm, rms_norm_reference,
            lambda fn: lambda q, g: jnp.sum(fn(q, g) * co),
            (x, gam), (0, 1)),
        "add_rms_norm": (
            add_rms_norm, add_rms_norm_reference,
            lambda fn: lambda q, dd, g: jnp.sum(fn(q, dd, g)[0] * co),
            (x, d, gam), (0, 1, 2)),
        "layer_norm": (
            layer_norm, layer_norm_reference,
            lambda fn: lambda q, g, b2: jnp.sum(fn(q, g, b2) * co),
            (x, gam, bet), (0, 1, 2)),
    }
    out = {"rows": rows, "features": feats,
           "platform": jax.devices()[0].platform,
           "interpret": _interpret(), "kernels": {}}
    prev = os.environ.get("DL4J_TPU_KERNEL_FORCE")
    try:
        # kernel pass: force the gate so the CPU (interpret) run
        # exercises the kernel path too; reference pass needs no gate
        os.environ["DL4J_TPU_KERNEL_FORCE"] = "1"
        for name, (fwd, ref_fwd, mk_loss, args, anums) in cases.items():
            ref_y = jax.jit(ref_fwd)(*args)
            ref_g = jax.jit(jax.grad(mk_loss(ref_fwd),
                                     argnums=anums))(*args)
            fallback_ms = timed(jax.jit(ref_fwd), *args)
            ker_y = jax.jit(fwd)(*args)
            ker_g = jax.jit(jax.grad(mk_loss(fwd),
                                     argnums=anums))(*args)
            err_f = float(jnp.max(jnp.abs(first(ker_y) - first(ref_y))))
            err_b = max(float(jnp.max(jnp.abs(a - b)))
                        for a, b in zip(ker_g, ref_g))
            rec = {
                "fwd_max_abs_err": err_f,
                "bwd_max_abs_err": err_b,
                "parity": "ok" if (err_f < 1e-4 and err_b < 1e-4)
                else "FAIL",
                "fallback_ms": round(fallback_ms, 3),
            }
            if not _interpret():
                rec["kernel_ms"] = round(timed(jax.jit(fwd), *args), 3)
            out["kernels"][name] = rec
    finally:
        if prev is None:
            os.environ.pop("DL4J_TPU_KERNEL_FORCE", None)
        else:
            os.environ["DL4J_TPU_KERNEL_FORCE"] = prev
    return out


def subprocess_report(timeout: int = 300):
    """Run :func:`fused_kernels_report` in a fresh forced-CPU process
    (the ``zero.subprocess_report`` pattern): callable from bench runs
    without touching their backend."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("DL4J_TPU_KERNEL_FORCE", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.ops.fused_norms"],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
    except (subprocess.TimeoutExpired, OSError) as e:
        return {"skipped": True, "reason": f"fused-kernels child: {e}"}
    parsed = None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except ValueError:
                pass
    if proc.returncode != 0 or parsed is None:
        tail = (proc.stderr or proc.stdout or "").strip()
        return {"skipped": True,
                "reason": "fused-kernels child rc=%d: %s"
                          % (proc.returncode, tail.splitlines()[-1]
                             if tail else "no output")}
    return parsed


def _main() -> None:
    import json

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    print(json.dumps(fused_kernels_report()))


if __name__ == "__main__":
    _main()
