"""Activation functions — reference: ``org.nd4j.linalg.activations.Activation``
enum + ``IActivation`` impls (~20 activations; nd4j-api
``org.nd4j.linalg.activations.impl.*``).

Each entry is a pure elementwise jnp function (XLA fuses these into the
surrounding matmul — no hand kernels needed on TPU). Gradients come from
jax autodiff; there is no per-activation ``backprop`` method as in the
reference.
"""
from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp


def identity(x):
    return x


def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jax.nn.relu6(x)


def leakyrelu(x, alpha: float = 0.01):
    return jax.nn.leaky_relu(x, alpha)


def elu(x, alpha: float = 1.0):
    return jax.nn.elu(x, alpha)


def selu(x):
    return jax.nn.selu(x)


def celu(x, alpha: float = 1.0):
    return jax.nn.celu(x, alpha)


def gelu(x):
    return jax.nn.gelu(x, approximate=False)


def gelu_tanh(x):
    return jax.nn.gelu(x, approximate=True)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def hardsigmoid_keras(x):
    # Keras-3 definition: relu6(x+3)/6 (slope 1/6, not the legacy 0.2)
    return jax.nn.relu6(x + 3.0) / 6.0


def tanh(x):
    return jnp.tanh(x)


def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def rationaltanh(x):
    # Reference RationalTanh: 1.7159 * tanh(2x/3) approximation family
    return 1.7159 * jnp.tanh(2.0 * x / 3.0)


def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def softmax(x, axis: int = -1):
    return jax.nn.softmax(x, axis=axis)


def logsoftmax(x, axis: int = -1):
    return jax.nn.log_softmax(x, axis=axis)


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def cube(x):
    return x ** 3


def swish(x):
    return jax.nn.silu(x)


def mish(x):
    return jax.nn.mish(x)


def thresholdedrelu(x, theta: float = 1.0):
    return jnp.where(x > theta, x, 0.0)


def clippedrelu(x, max_value: float = 6.0):
    """ReLU capped at ``max_value`` (Keras ReLU(max_value=m); the
    reference's ActivationReLU with a cap). ``relu6`` is the m=6
    special case."""
    return jnp.clip(x, 0.0, max_value)


_REGISTRY: Dict[str, Callable] = {
    "identity": identity,
    "linear": identity,
    "relu": relu,
    "relu6": relu6,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "selu": selu,
    "celu": celu,
    "gelu": gelu,
    "gelu_tanh": gelu_tanh,
    "sigmoid": sigmoid,
    "hardsigmoid": hardsigmoid,
    "hardsigmoid_keras": hardsigmoid_keras,
    "tanh": tanh,
    "hardtanh": hardtanh,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "softmax": softmax,
    "logsoftmax": logsoftmax,
    "softplus": softplus,
    "softsign": softsign,
    "cube": cube,
    "swish": swish,
    "silu": swish,
    "mish": mish,
    "thresholdedrelu": thresholdedrelu,
    "clippedrelu": clippedrelu,
}


def get(name_or_fn) -> Callable:
    """Resolve an activation by reference enum name (case-insensitive).

    A ``name:param`` suffix parametrizes alpha-style activations
    (``"leakyrelu:0.3"``, ``"elu:0.5"``) — serializable in layer
    configs, used by the Keras importer.
    """
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if ":" in key:
        base, _, arg = key.partition(":")
        alpha = float(arg)
        if base in ("leakyrelu", "elu", "celu", "thresholdedrelu",
                    "clippedrelu"):
            fn = _REGISTRY[base]
            return lambda x: fn(x, alpha)
        raise ValueError(f"activation {base!r} takes no parameter")
    if key not in _REGISTRY:
        raise ValueError(f"Unknown activation {name_or_fn!r}; "
                         f"known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def names():
    return sorted(_REGISTRY)
