"""Op surface: activations, losses, attention, compression, random.

Reference: libnd4j declarable ops (~500, ``include/ops/declarable/generic``)
+ nd4j op class hierarchy. On TPU nearly all of this surface is XLA via
jax.numpy/lax; this package holds the framework-level ops (activations,
losses, attention, gradient compression) with reference-parity names.
"""
from deeplearning4j_tpu.ops import activations, losses

__all__ = ["activations", "losses"]
