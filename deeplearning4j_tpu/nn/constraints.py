"""Parameter constraints + weight noise — reference:
``org.deeplearning4j.nn.api.layers.LayerConstraint``
(MaxNormConstraint, MinMaxNormConstraint, NonNegativeConstraint,
UnitNormConstraint — applied to parameters AFTER each updater step,
SURVEY §2.3 config-system row) and
``org.deeplearning4j.nn.conf.weightnoise`` (WeightNoise, DropConnect —
parameters perturbed during the training forward pass only).

Both are pure functions of the param pytree, applied inside the jitted
train step: constraints right after ``optax.apply_updates``, weight
noise right before the forward. By default they touch weight matrices
only (param keys not named like biases/norm-scales), matching the
reference's ``applyToWeights``-default.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

# params that are NOT weights (bias / norm scale-shift / running aux)
_NON_WEIGHT_KEYS = {"b", "bo", "beta", "gamma", "g", "rb", "P"}


def _is_weight(key: str) -> bool:
    return key not in _NON_WEIGHT_KEYS


def _map_weights(fn, params, apply_to_bias=False):
    def rec(tree):
        if isinstance(tree, dict):
            return {k: (rec(v) if isinstance(v, dict)
                        else (fn(v) if (apply_to_bias or _is_weight(k))
                              else v))
                    for k, v in tree.items()}
        return tree
    return rec(params)


_CONSTRAINTS: Dict[str, type] = {}


def _register(cls):
    _CONSTRAINTS[cls.__name__] = cls
    return cls


@dataclass
class BaseConstraint:
    apply_to_bias: bool = False

    def constrain(self, p):
        raise NotImplementedError

    def apply(self, params):
        return _map_weights(self.constrain, params, self.apply_to_bias)

    def to_dict(self):
        return {"@class": type(self).__name__, **self.__dict__}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "BaseConstraint":
        d = dict(d)
        kind = d.pop("@class")
        return _CONSTRAINTS[kind](**d)


def _axis_norms(p, eps=1e-12):
    # norm over all axes except the last (output/feature axis) —
    # reference constraints normalize per output unit
    axes = tuple(range(p.ndim - 1)) if p.ndim > 1 else (0,)
    return jnp.sqrt(jnp.sum(jnp.square(p), axis=axes, keepdims=True)
                    ) + eps


@_register
@dataclass
class MaxNormConstraint(BaseConstraint):
    """Reference MaxNormConstraint: rescale columns whose norm exceeds
    ``max_norm``."""
    max_norm: float = 2.0

    def constrain(self, p):
        n = _axis_norms(p)
        return p * jnp.minimum(1.0, self.max_norm / n)


@_register
@dataclass
class MinMaxNormConstraint(BaseConstraint):
    """Reference MinMaxNormConstraint: clamp column norms into
    [min_norm, max_norm], interpolated by ``rate``."""
    min_norm: float = 0.5
    max_norm: float = 2.0
    rate: float = 1.0

    def constrain(self, p):
        n = _axis_norms(p)
        clipped = jnp.clip(n, self.min_norm, self.max_norm)
        target = self.rate * clipped + (1.0 - self.rate) * n
        return p * (target / n)


@_register
@dataclass
class NonNegativeConstraint(BaseConstraint):
    """Reference NonNegativeConstraint: clip params at zero."""

    def constrain(self, p):
        return jnp.maximum(p, 0.0)


@_register
@dataclass
class UnitNormConstraint(BaseConstraint):
    """Reference UnitNormConstraint: rescale every column to norm 1."""

    def constrain(self, p):
        return p / _axis_norms(p)


# ---------------------------------------------------------------------------
# weight noise
# ---------------------------------------------------------------------------
_NOISES: Dict[str, type] = {}


def _register_noise(cls):
    _NOISES[cls.__name__] = cls
    return cls


@dataclass
class BaseWeightNoise:
    apply_to_bias: bool = False

    def perturb(self, p, rng):
        raise NotImplementedError

    def apply(self, params, rng):
        # single traversal: fold a fresh key per perturbed leaf
        key_box = [rng]

        def perturb(p):
            key_box[0], sub = jax.random.split(key_box[0])
            return self.perturb(p, sub)
        return _map_weights(perturb, params, self.apply_to_bias)

    def to_dict(self):
        return {"@class": type(self).__name__, **self.__dict__}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "BaseWeightNoise":
        d = dict(d)
        kind = d.pop("@class")
        return _NOISES[kind](**d)


@_register_noise
@dataclass
class WeightNoise(BaseWeightNoise):
    """Reference WeightNoise: gaussian noise on weights during the
    training forward — additive (w + n) or multiplicative (w * (1+n))."""
    stddev: float = 0.01
    mean: float = 0.0
    additive: bool = True

    def perturb(self, p, rng):
        n = self.mean + self.stddev * jax.random.normal(rng, p.shape,
                                                        p.dtype)
        return p + n if self.additive else p * (1.0 + n)


@_register_noise
@dataclass
class DropConnect(BaseWeightNoise):
    """Reference DropConnect: bernoulli mask on weights (inverted
    scaling) during the training forward."""
    weight_retain_prob: float = 0.5

    def perturb(self, p, rng):
        keep = self.weight_retain_prob
        m = jax.random.bernoulli(rng, keep, p.shape)
        return jnp.where(m, p / keep, 0.0).astype(p.dtype)
