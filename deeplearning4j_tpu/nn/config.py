"""Network configuration — reference:
``org.deeplearning4j.nn.conf.NeuralNetConfiguration`` (+``.Builder``,
``.ListBuilder``), ``MultiLayerConfiguration``, ``inputs.InputType``.

Fluent builder → JSON round-trip (the reference serializes Jackson beans;
here plain dicts via each bean's ``to_dict``/``from_dict``). Global
defaults (activation, weight init, updater, l1/l2, dropout) flow into
layers that don't override them, mirroring
``NeuralNetConfiguration.Builder.layer(...)`` cloning semantics.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.nn.layers.base import Layer, layer_from_dict
from deeplearning4j_tpu.nn import updaters as upd


class InputType:
    """Shape descriptor (reference inputs.InputType). Shapes exclude the
    batch axis; layouts are channels-last (TPU-first)."""

    def __init__(self, kind: str, shape: Tuple[int, ...]):
        self.kind = kind
        self.shape = tuple(int(s) for s in shape)

    @staticmethod
    def feed_forward(n: int) -> "InputType":
        return InputType("ff", (n,))

    @staticmethod
    def recurrent(n_features: int, timesteps: int = -1) -> "InputType":
        return InputType("rnn", (timesteps, n_features))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        # NOTE: reference order is (h, w, c) with NCHW data; ours is NHWC.
        return InputType("cnn", (height, width, channels))

    @staticmethod
    def convolutional_3d(d: int, h: int, w: int, c: int) -> "InputType":
        return InputType("cnn3d", (d, h, w, c))

    def to_dict(self):
        return {"kind": self.kind, "shape": list(self.shape)}

    @staticmethod
    def from_dict(d):
        return InputType(d["kind"], tuple(d["shape"]))

    def __repr__(self):
        return f"InputType({self.kind}, {self.shape})"


_GLOBAL_DEFAULTS = ("activation", "weight_init", "l1", "l2",
                    "weight_decay", "dropout")


@dataclass
class MultiLayerConfiguration:
    """Reference: MultiLayerConfiguration. Built via
    ``NeuralNetConfiguration.builder()...list()...build()``."""
    layers: List[Layer] = field(default_factory=list)
    seed: int = 12345
    dtype: str = "float32"
    compute_dtype: Optional[str] = None   # bf16 fwd/bwd, fp32 params
    updater: Any = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    input_type: Optional[InputType] = None
    backprop_type: str = "Standard"        # or "TruncatedBPTT"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    mini_batch: bool = True
    # per-layer-index input preprocessors (reference
    # ListBuilder.inputPreProcessor(idx, proc))
    input_preprocessors: dict = field(default_factory=dict)
    # weight tying: [dst_layer, dst_param, src_layer, src_param,
    # transpose] entries — the dst param is NOT a master parameter; it
    # is materialised from src inside every forward (so gradients
    # accumulate onto src from both uses). The classic use is a causal
    # LM's tied embedding/output head (GPT-2/LLaMA convention; no
    # reference analog — its DL4J-era models never tie).
    tied_weights: List[list] = field(default_factory=list)

    def __post_init__(self):
        if self.updater is None:
            self.updater = upd.Sgd(learning_rate=1e-2)

    # -- serialization -----------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "layers": [l.to_dict() for l in self.layers],
            "seed": self.seed,
            "dtype": self.dtype,
            "compute_dtype": self.compute_dtype,
            "updater": self.updater.to_dict(),
            "gradient_normalization": self.gradient_normalization,
            "gradient_normalization_threshold":
                self.gradient_normalization_threshold,
            "input_type": self.input_type.to_dict()
                if self.input_type else None,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "input_preprocessors": {
                str(i): p.to_dict()
                for i, p in self.input_preprocessors.items()},
            "tied_weights": [list(t) for t in self.tied_weights],
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        d = json.loads(s)
        conf = MultiLayerConfiguration(
            layers=[layer_from_dict(ld) for ld in d["layers"]],
            seed=d.get("seed", 12345),
            dtype=d.get("dtype", "float32"),
            compute_dtype=d.get("compute_dtype"),
            updater=upd.updater_from_dict(d["updater"]),
            gradient_normalization=d.get("gradient_normalization"),
            gradient_normalization_threshold=d.get(
                "gradient_normalization_threshold", 1.0),
            backprop_type=d.get("backprop_type", "Standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
        )
        it = d.get("input_type")
        if it:
            conf.input_type = InputType.from_dict(it)
        pp = d.get("input_preprocessors")
        if pp:
            from deeplearning4j_tpu.nn.preprocessors import (
                preprocessor_from_dict)
            conf.input_preprocessors = {
                int(i): preprocessor_from_dict(pd)
                for i, pd in pp.items()}
        conf.tied_weights = [list(t) for t in d.get("tied_weights", [])]
        return conf


class ListBuilder:
    """Reference: NeuralNetConfiguration.ListBuilder."""

    def __init__(self, global_conf: "NeuralNetConfiguration"):
        self._g = global_conf
        self._layers: List[Layer] = []
        self._input_type: Optional[InputType] = None
        self._preprocessors: dict = {}
        self._tied: List[list] = []

    def layer(self, *args) -> "ListBuilder":
        """layer(l) or layer(index, l) like the reference."""
        l = args[-1]
        # flow global defaults into unset layer fields
        for name in _GLOBAL_DEFAULTS:
            if getattr(l, name, None) is None:
                gv = getattr(self._g, name, None)
                if gv is not None:
                    setattr(l, name, gv)
        if len(args) == 2:
            idx = args[0]
            while len(self._layers) <= idx:
                self._layers.append(None)  # type: ignore
            self._layers[idx] = l
        else:
            self._layers.append(l)
        return self

    def set_input_type(self, input_type: InputType) -> "ListBuilder":
        self._input_type = input_type
        return self

    def input_pre_processor(self, idx: int, proc) -> "ListBuilder":
        """Attach an InputPreProcessor before layer ``idx`` (reference
        ListBuilder.inputPreProcessor)."""
        self._preprocessors[idx] = proc
        return self

    def tie_weights(self, dst_layer: int, dst_param: str,
                    src_layer: int, src_param: str,
                    transpose: bool = False) -> "ListBuilder":
        """Tie layer ``dst_layer``'s ``dst_param`` to ``src_layer``'s
        ``src_param`` (optionally transposed): the dst param stops
        being a trainable master parameter and is rebuilt from src in
        every forward — gradients flow to src from both uses. The
        embedding/LM-head tie (GPT-2 convention) is the canonical
        case."""
        self._tied.append([dst_layer, dst_param, src_layer, src_param,
                           bool(transpose)])
        return self

    def backprop_type(self, t: str) -> "ListBuilder":
        self._g.backprop_type_ = t
        return self

    def tbptt_fwd_length(self, k: int) -> "ListBuilder":
        self._g.tbptt_fwd_ = k
        return self

    def tbptt_back_length(self, k: int) -> "ListBuilder":
        self._g.tbptt_back_ = k
        return self

    def build(self) -> MultiLayerConfiguration:
        if any(l is None for l in self._layers):
            raise ValueError("gap in layer indices")
        return MultiLayerConfiguration(
            layers=self._layers,
            seed=self._g.seed_,
            dtype=self._g.dtype_,
            compute_dtype=self._g.compute_dtype_,
            updater=self._g.updater_,
            gradient_normalization=self._g.grad_norm_,
            gradient_normalization_threshold=self._g.grad_norm_threshold_,
            input_type=self._input_type,
            backprop_type=self._g.backprop_type_,
            tbptt_fwd_length=self._g.tbptt_fwd_,
            tbptt_back_length=self._g.tbptt_back_,
            input_preprocessors=dict(self._preprocessors),
            tied_weights=[list(t) for t in self._tied],
        )


class NeuralNetConfiguration:
    """Reference: NeuralNetConfiguration.Builder (fluent global config)."""

    def __init__(self):
        self.seed_ = 12345
        self.dtype_ = "float32"
        self.compute_dtype_ = None
        self.updater_ = upd.Sgd(learning_rate=1e-2)
        self.activation = None
        self.weight_init = None
        self.l1 = None
        self.l2 = None
        self.weight_decay = None
        self.dropout = None
        self.grad_norm_ = None
        self.grad_norm_threshold_ = 1.0
        self.backprop_type_ = "Standard"
        self.tbptt_fwd_ = 20
        self.tbptt_back_ = 20

    @staticmethod
    def builder() -> "NeuralNetConfiguration":
        return NeuralNetConfiguration()

    def seed(self, s: int):
        self.seed_ = int(s)
        return self

    def data_type(self, dtype: str):
        self.dtype_ = dtype
        return self

    def compute_data_type(self, dtype: Optional[str]):
        """Mixed precision: run forward/backward math in ``dtype``
        (bfloat16 on TPU — MXU-native) while params, optimizer state
        and the loss stay in ``data_type`` (fp32). The reference has no
        equivalent (nd4j global dtype changes params too); this is the
        TPU-idiomatic split."""
        self.compute_dtype_ = dtype
        return self

    def updater(self, u):
        self.updater_ = u
        return self

    def activation_fn(self, a: str):
        self.activation = a
        return self

    def weight_init_fn(self, w: str):
        self.weight_init = w
        return self

    def l1_(self, v: float):
        self.l1 = v
        return self

    def l2_(self, v: float):
        self.l2 = v
        return self

    def weight_decay_(self, v: float):
        self.weight_decay = v
        return self

    def dropout_(self, v: float):
        self.dropout = v
        return self

    def gradient_normalization(self, mode: str, threshold: float = 1.0):
        self.grad_norm_ = mode
        self.grad_norm_threshold_ = threshold
        return self

    def list(self) -> ListBuilder:
        return ListBuilder(self)

    def graph_builder(self):
        """Reference: NeuralNetConfiguration.Builder.graphBuilder()."""
        from deeplearning4j_tpu.nn.graph import GraphBuilder
        return GraphBuilder(self)
