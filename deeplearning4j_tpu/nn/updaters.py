"""Updaters (optimizers) — reference: ``org.nd4j.linalg.learning.config.IUpdater``
beans (Adam, AdamW, Nadam, AMSGrad, Nesterovs, RmsProp, AdaGrad, AdaDelta,
Sgd, NoOp) + ``org.nd4j.linalg.schedule.ISchedule`` impls, and the dl4j-side
``BaseMultiLayerUpdater``/``UpdaterBlock`` plumbing (per-layer LR,
regularization applied inside updater blocks, gradient clipping/
normalization modes).

TPU-native: each bean maps to an optax GradientTransformation; the
network builds ONE optax optimizer over the whole param pytree with
per-layer overrides via ``optax.multi_transform`` — the update runs
inside the jitted train step.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import optax

_UPDATER_REGISTRY: Dict[str, type] = {}


def register_updater(cls):
    _UPDATER_REGISTRY[cls.__name__] = cls
    return cls


def updater_from_dict(d):
    if isinstance(d, Updater):
        return d
    d = dict(d)
    cls = _UPDATER_REGISTRY[d.pop("@class")]
    if "schedule" in d and isinstance(d["schedule"], dict):
        d["schedule"] = schedule_from_dict(d["schedule"])
    return cls(**d)


# ---------------------------------------------------------------------------
# Schedules — reference org.nd4j.linalg.schedule.*
# ---------------------------------------------------------------------------

_SCHEDULE_REGISTRY: Dict[str, type] = {}


def register_schedule(cls):
    _SCHEDULE_REGISTRY[cls.__name__] = cls
    return cls


def schedule_from_dict(d):
    d = dict(d)
    cls = _SCHEDULE_REGISTRY[d.pop("@class")]
    if isinstance(d.get("base"), dict) and "@class" in d["base"]:
        d["base"] = schedule_from_dict(d["base"])   # nested warmup base
    return cls(**d)


@dataclass
class Schedule:
    def __call__(self, step):
        raise NotImplementedError

    def to_dict(self):
        import dataclasses as dc
        out = {"@class": type(self).__name__}
        out.update(dc.asdict(self))
        return out


@register_schedule
@dataclass
class FixedSchedule(Schedule):
    value: float = 1e-3

    def __call__(self, step):
        return self.value


@register_schedule
@dataclass
class StepSchedule(Schedule):
    """lr * decay^floor(step / interval) (reference StepSchedule)."""
    initial: float = 1e-3
    decay_rate: float = 0.5
    step: int = 1000

    def __call__(self, step):
        return self.initial * self.decay_rate ** jnp.floor(step / self.step)


@register_schedule
@dataclass
class ExponentialSchedule(Schedule):
    initial: float = 1e-3
    gamma: float = 0.99

    def __call__(self, step):
        return self.initial * self.gamma ** step


@register_schedule
@dataclass
class InverseSchedule(Schedule):
    initial: float = 1e-3
    gamma: float = 0.99
    power: float = 1.0

    def __call__(self, step):
        return self.initial / (1 + self.gamma * step) ** self.power


@register_schedule
@dataclass
class PolySchedule(Schedule):
    initial: float = 1e-3
    power: float = 2.0
    max_iter: int = 10000

    def __call__(self, step):
        frac = jnp.clip(step / self.max_iter, 0.0, 1.0)
        return self.initial * (1 - frac) ** self.power


@register_schedule
@dataclass
class SigmoidSchedule(Schedule):
    initial: float = 1e-3
    gamma: float = 0.01
    step_center: int = 1000

    def __call__(self, step):
        return self.initial / (1 + jnp.exp(
            self.gamma * (step - self.step_center)))


@register_schedule
@dataclass
class CosineSchedule(Schedule):
    """Cosine decay (modern addition; reference has CycleSchedule)."""
    initial: float = 1e-3
    max_iter: int = 10000
    final_fraction: float = 0.0

    def __call__(self, step):
        frac = jnp.clip(step / self.max_iter, 0.0, 1.0)
        cos = 0.5 * (1 + jnp.cos(jnp.pi * frac))
        return self.initial * (self.final_fraction +
                               (1 - self.final_fraction) * cos)


@register_schedule
@dataclass
class WarmupSchedule(Schedule):
    """Linear warmup into another schedule (transformer-era addition).
    ``base``: a constant rate or any Schedule; defaults to 1e-3."""
    warmup_steps: int = 1000
    base: Any = 1e-3

    def __call__(self, step):
        base = (self.base(step) if callable(self.base)
                else float(self.base))
        return base * jnp.minimum(1.0, (step + 1) / self.warmup_steps)

    def to_dict(self):
        d = super().to_dict()
        if isinstance(self.base, Schedule):
            d["base"] = self.base.to_dict()
        return d


# ---------------------------------------------------------------------------
# Updater beans
# ---------------------------------------------------------------------------

@dataclass
class Updater:
    learning_rate: float = 1e-3
    schedule: Optional[Schedule] = None

    def _lr(self):
        if self.schedule is not None:
            return lambda step: self.schedule(step)
        return self.learning_rate

    def to_optax(self) -> optax.GradientTransformation:
        raise NotImplementedError

    def to_dict(self):
        import dataclasses as dc
        out = {"@class": type(self).__name__}
        for f in dc.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Schedule):
                v = v.to_dict()
            out[f.name] = v
        return out


@register_updater
@dataclass
class Sgd(Updater):
    def to_optax(self):
        return optax.sgd(self._lr())


@register_updater
@dataclass
class Adam(Updater):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adam(self._lr(), b1=self.beta1, b2=self.beta2,
                          eps=self.epsilon)


@register_updater
@dataclass
class AdamW(Adam):
    weight_decay: float = 0.01
    # BERT-recipe decay masking: keys named like biases (b, bo, b1...)
    # or LayerNorm scales (gamma/beta) are excluded from decay
    exclude_bias_and_norm: bool = False

    def to_optax(self):
        mask = None
        if self.exclude_bias_and_norm:
            def _decay_leaf(path):
                key = str(path[-1].key if hasattr(path[-1], "key")
                          else path[-1])
                return not (key.startswith("b") or
                            key in ("gamma", "beta"))

            def mask(params):
                import jax
                return jax.tree_util.tree_map_with_path(
                    lambda p, _: _decay_leaf(p), params)
        return optax.adamw(self._lr(), b1=self.beta1, b2=self.beta2,
                           eps=self.epsilon,
                           weight_decay=self.weight_decay, mask=mask)


@register_updater
@dataclass
class Nadam(Adam):
    def to_optax(self):
        return optax.nadam(self._lr(), b1=self.beta1, b2=self.beta2,
                           eps=self.epsilon)


@register_updater
@dataclass
class AMSGrad(Adam):
    def to_optax(self):
        return optax.amsgrad(self._lr(), b1=self.beta1, b2=self.beta2,
                             eps=self.epsilon)


@register_updater
@dataclass
class Nesterovs(Updater):
    momentum: float = 0.9

    def to_optax(self):
        return optax.sgd(self._lr(), momentum=self.momentum, nesterov=True)


@register_updater
@dataclass
class Momentum(Updater):
    momentum: float = 0.9

    def to_optax(self):
        return optax.sgd(self._lr(), momentum=self.momentum)


@register_updater
@dataclass
class RmsProp(Updater):
    decay: float = 0.95
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.rmsprop(self._lr(), decay=self.decay,
                             eps=self.epsilon)


@register_updater
@dataclass
class AdaGrad(Updater):
    epsilon: float = 1e-6

    def to_optax(self):
        return optax.adagrad(self._lr(), eps=self.epsilon)


@register_updater
@dataclass
class AdaDelta(Updater):
    rho: float = 0.95
    epsilon: float = 1e-6

    def to_optax(self):
        return optax.adadelta(learning_rate=1.0, rho=self.rho,
                              eps=self.epsilon)


@register_updater
@dataclass
class AdaMax(Adam):
    def to_optax(self):
        return optax.adamax(self._lr(), b1=self.beta1, b2=self.beta2,
                            eps=self.epsilon)


@register_updater
@dataclass
class NoOp(Updater):
    def to_optax(self):
        return optax.set_to_zero()


# ---------------------------------------------------------------------------
# Gradient normalization — reference GradientNormalization enum
# (BaseLayer.gradientNormalization): RenormalizeL2PerLayer/PerParamType,
# ClipElementWiseAbsoluteValue, ClipL2PerLayer, ClipL2PerParamType.
# ---------------------------------------------------------------------------

def gradient_normalization(mode: Optional[str], threshold: float = 1.0):
    """Returns an optax transform implementing the reference modes."""
    if mode is None or mode == "None":
        return optax.identity()
    mode_l = str(mode).lower()
    if mode_l == "clipelementwiseabsolutevalue":
        return optax.clip(threshold)
    if mode_l == "clipl2perlayer":
        def clip_leaf(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g)) + 1e-12)
            return g * jnp.minimum(1.0, threshold / n)
        return optax.stateless(lambda g, p: jax.tree.map(clip_leaf, g))
    if mode_l == "clipl2perparamtype":
        return optax.clip_by_global_norm(threshold)
    if mode_l == "renormalizel2perlayer":
        def renorm(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g)) + 1e-12)
            return g / n
        return optax.stateless(lambda g, p: jax.tree.map(renorm, g))
    if mode_l == "renormalizel2perparamtype":
        def renorm_all(g, p):
            n = optax.global_norm(g)
            return jax.tree.map(lambda x: x / (n + 1e-12), g)
        return optax.stateless(renorm_all)
    raise ValueError(f"unknown gradient normalization {mode!r}")


def l1_l2_regularization(l1: float = 0.0, l2: float = 0.0,
                         weight_decay: float = 0.0):
    """Reference semantics: l1/l2 penalties added to gradients inside the
    updater block (Regularization.applyStep BEFORE_UPDATER); weight decay
    applied decoupled."""
    transforms = []
    if l1 or l2:
        def add_reg(g, p):
            def leaf(gi, pi):
                out = gi
                if l2:
                    out = out + l2 * pi
                if l1:
                    out = out + l1 * jnp.sign(pi)
                return out
            return jax.tree.map(leaf, g, p)
        transforms.append(optax.stateless(add_reg))
    if weight_decay:
        transforms.append(optax.add_decayed_weights(weight_decay))
    return optax.chain(*transforms) if transforms else optax.identity()
