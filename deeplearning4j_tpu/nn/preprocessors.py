"""Input preprocessors — shape adapters between layer families.

Reference: ``org.deeplearning4j.nn.conf.preprocessor.*``
(CnnToFeedForwardPreProcessor, FeedForwardToCnnPreProcessor,
RnnToFeedForwardPreProcessor, FeedForwardToRnnPreProcessor,
CnnToRnnPreProcessor, RnnToCnnPreProcessor) attached per layer via
``ListBuilder.inputPreProcessor(idx, proc)``.

TPU-native design: each preprocessor is a pure reshape/transpose XLA
fuses into the neighbouring ops — zero-cost at runtime, but preserved
as named config beans for JSON round-trip parity.  Layout note: the
reference is NCHW / [B,F,T]; here CNN tensors are NHWC and sequences
are [B,T,F] (TPU-friendly layouts), so the "same" preprocessor permutes
differently — semantics (which axes merge) match, layout does not.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Sequence

import jax.numpy as jnp

_PREPROC_REGISTRY: Dict[str, type] = {}


def register_preprocessor(cls):
    _PREPROC_REGISTRY[cls.__name__] = cls
    return cls


def preprocessor_from_dict(d: Dict[str, Any]):
    d = dict(d)
    cls = _PREPROC_REGISTRY[d.pop("@class")]
    return cls(**{k: v for k, v in d.items()
                  if k in {f.name for f in dataclasses.fields(cls)}})


@dataclass
class InputPreProcessor:
    """pre_process transforms activations; output_shape mirrors it on
    (batch-less) shapes; propagate_mask adapts the [B,T] mask."""

    def pre_process(self, x):
        raise NotImplementedError

    def output_shape(self, input_shape: Sequence[int]) -> tuple:
        raise NotImplementedError

    def propagate_mask(self, mask):
        return mask

    def to_dict(self):
        out = {"@class": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            out[f.name] = list(v) if isinstance(v, tuple) else v
        return out


@register_preprocessor
@dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    """[B, H, W, C] → [B, H*W*C] (reference CnnToFeedForwardPreProcessor)."""

    def pre_process(self, x):
        return x.reshape(x.shape[0], -1)

    def output_shape(self, s):
        return (int(s[0]) * int(s[1]) * int(s[2]),)

    def propagate_mask(self, mask):
        return None


@register_preprocessor
@dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    """[B, H*W*C] → [B, H, W, C] (reference FeedForwardToCnnPreProcessor;
    NHWC here vs the reference's NCHW)."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x):
        return x.reshape(x.shape[0], self.height, self.width,
                         self.channels)

    def output_shape(self, s):
        return (self.height, self.width, self.channels)


@register_preprocessor
@dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[B, T, F] → [B*T, F]: timestep-wise dense over sequences
    (reference RnnToFeedForwardPreProcessor)."""

    def pre_process(self, x):
        return x.reshape(-1, x.shape[-1])

    def output_shape(self, s):
        return (int(s[-1]),)

    def propagate_mask(self, mask):
        return None if mask is None else mask.reshape(-1)


@register_preprocessor
@dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[B*T, F] → [B, T, F] (reference FeedForwardToRnnPreProcessor)."""
    time_steps: int = 0

    def pre_process(self, x):
        return x.reshape(-1, self.time_steps, x.shape[-1])

    def output_shape(self, s):
        return (self.time_steps, int(s[-1]))

    def propagate_mask(self, mask):
        return None if mask is None else mask.reshape(
            -1, self.time_steps)


@register_preprocessor
@dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    """[B, H, W, C] → [B, H, W*C]: rows become timesteps (reference
    CnnToRnnPreProcessor merges spatial dims into a time axis)."""

    def pre_process(self, x):
        return x.reshape(x.shape[0], x.shape[1], -1)

    def output_shape(self, s):
        return (int(s[0]), int(s[1]) * int(s[2]))


@register_preprocessor
@dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    """[B, T, F] → [B, T, W, C] with F = W*C (reference
    RnnToCnnPreProcessor)."""
    width: int = 0
    channels: int = 0

    def pre_process(self, x):
        return x.reshape(x.shape[0], x.shape[1], self.width,
                         self.channels)

    def output_shape(self, s):
        return (int(s[0]), self.width, self.channels)

    def propagate_mask(self, mask):
        return None


@register_preprocessor
@dataclass
class ComposableInputPreProcessor(InputPreProcessor):
    """Chain of preprocessors (reference ComposableInputPreProcessor)."""
    processors: Sequence[Any] = ()

    def pre_process(self, x):
        for p in self.processors:
            x = p.pre_process(x)
        return x

    def output_shape(self, s):
        for p in self.processors:
            s = p.output_shape(s)
        return s

    def propagate_mask(self, mask):
        for p in self.processors:
            mask = p.propagate_mask(mask)
        return mask

    def to_dict(self):
        return {"@class": type(self).__name__,
                "processors": [p.to_dict() for p in self.processors]}
