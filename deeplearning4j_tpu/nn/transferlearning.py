"""Transfer learning (reference: ``deeplearning4j-nn``
``org.deeplearning4j.nn.transferlearning.TransferLearning`` (+``.Builder``
and ``.GraphBuilder``), ``FineTuneConfiguration``,
``TransferLearningHelper``).

Builds a NEW network from a trained one: freeze a feature-extractor
prefix, swap/replace output heads, append layers — keeping trained
params for retained layers and re-initializing new/modified ones. The
pytree param structure makes the surgery trivial compared to the
reference's flattened-view bookkeeping.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from deeplearning4j_tpu.nn.layers import FrozenLayer
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, _lname
from deeplearning4j_tpu import dtypes


@dataclass
class FineTuneConfiguration:
    """Overrides applied to every *unfrozen* layer of the new net
    (reference FineTuneConfiguration)."""
    updater: Any = None
    learning_rate: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    weight_decay: Optional[float] = None
    dropout: Optional[float] = None
    seed: Optional[int] = None

    def _apply(self, conf, layers: List[Layer]):
        if self.updater is not None:
            conf.updater = self.updater
        if self.seed is not None:
            conf.seed = self.seed
        for layer in layers:
            if isinstance(layer, FrozenLayer):
                continue
            if self.learning_rate is not None:
                layer.learning_rate = self.learning_rate
            for f in ("l1", "l2", "weight_decay", "dropout"):
                v = getattr(self, f)
                if v is not None:
                    setattr(layer, f, v)


class TransferLearning:
    """Reference: TransferLearning.Builder (MultiLayerNetwork flavor)."""

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            if not net.params:
                raise ValueError("source network is not initialized")
            self._net = net
            self._ftc: Optional[FineTuneConfiguration] = None
            self._freeze_until: Optional[int] = None
            self._n_removed = 0
            self._appended: List[Layer] = []
            self._replacements: Dict[int, Layer] = {}
            self._nout_replace: Dict[int, tuple] = {}

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._ftc = ftc
            return self

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers [0..layer_idx] (reference
            setFeatureExtractor: 'frozen up to and including')."""
            self._freeze_until = layer_idx
            return self

        def remove_output_layer(self):
            self._n_removed += 1
            return self

        def remove_layers_from_output(self, n: int):
            self._n_removed += int(n)
            return self

        def add_layer(self, layer: Layer):
            self._appended.append(layer)
            return self

        def replace_layer(self, idx: int, layer: Layer):
            self._replacements[idx] = layer
            return self

        def n_out_replace(self, idx: int, n_out: int,
                          weight_init: Optional[str] = None):
            """Change layer idx's output width, re-initializing it AND
            the next layer's input side (reference nOutReplace)."""
            self._nout_replace[idx] = (int(n_out), weight_init)
            return self

        def build(self) -> MultiLayerNetwork:
            src = self._net
            n_keep = len(src.layers) - self._n_removed
            if n_keep < 0:
                raise ValueError("removed more layers than the net has")

            layers: List[Layer] = [copy.deepcopy(l)
                                   for l in src.layers[:n_keep]]
            # carry trained params/state for kept layers — as COPIES:
            # the new net's jitted step donates its buffers, which must
            # not delete the source net's arrays out from under it
            import jax.numpy as jnp
            params = {_lname(i): jax.tree.map(jnp.array,
                                              src.params[_lname(i)])
                      for i in range(n_keep)}
            state = {_lname(i): jax.tree.map(jnp.array,
                                             src.state[_lname(i)])
                     for i in range(n_keep)}
            reinit = set()        # our indices needing fresh params

            for idx, layer in self._replacements.items():
                if idx >= n_keep:
                    raise ValueError(f"replace_layer({idx}) out of range")
                layers[idx] = copy.deepcopy(layer)
                reinit.add(idx)

            for idx, (n_out, winit) in self._nout_replace.items():
                if idx >= n_keep:
                    raise ValueError(f"n_out_replace({idx}) out of range")
                layers[idx] = copy.deepcopy(layers[idx])
                layers[idx].n_out = n_out
                if winit:
                    layers[idx].weight_init = winit
                reinit.add(idx)
                if idx + 1 < n_keep:
                    reinit.add(idx + 1)     # input side changed

            base = len(layers)
            layers.extend(copy.deepcopy(l) for l in self._appended)
            reinit.update(range(base, len(layers)))

            if self._freeze_until is not None:
                for i in range(min(self._freeze_until + 1, len(layers))):
                    if not isinstance(layers[i], FrozenLayer):
                        layers[i] = FrozenLayer(underlying=layers[i])

            conf = copy.deepcopy(src.conf)
            conf.layers = layers
            # tie entries survive ONLY when both endpoints are kept,
            # un-replaced, un-reinitialized layers; a tie touching a
            # removed or fresh layer is dropped (the canonical
            # head-swap on a tied LM gets an ordinary fresh head —
            # silently re-tying it would shadow its new params)
            old_ties = list(getattr(conf, "tied_weights", []))
            conf.tied_weights = [
                t for t in old_ties
                if (t[0] < n_keep and t[2] < n_keep
                    and t[0] not in reinit and t[2] not in reinit)]
            # a tie dropped because its SOURCE went away, whose dst
            # layer is kept untouched, must not silently lose the
            # trained weights: materialize the old tied value (from
            # the source's trained masters, transposed per the tie)
            # into the dst param so the kept layer keeps computing
            # what it computed before the surgery
            surviving = {(t[0], t[1]) for t in conf.tied_weights}
            dropped_fill = {}
            for di, dn, si, sn, tr in old_ties:
                if ((di, dn) in surviving or di >= n_keep
                        or di in reinit):
                    continue
                # read from the SOURCE net's full params (the local
                # `params` dict only carries kept layers — a tie whose
                # source layer was REMOVED is exactly the case that
                # needs this fill); copy so the new net's donated
                # buffers never alias the source net's arrays
                src_p = src.params.get(_lname(si), {})
                if sn in src_p:
                    val = jnp.array(src_p[sn])
                    dropped_fill[(di, dn)] = val.T if tr else val
            if self._ftc is not None:
                self._ftc._apply(conf, layers)

            new = MultiLayerNetwork(conf)
            # shape-infer through the stack, initializing only what needs
            # fresh params
            dtype = dtypes.resolve(conf.dtype)
            key = jax.random.PRNGKey(conf.seed + 1)
            shape = src._input_shape
            new._input_shape = shape
            new._layer_shapes = []
            tied_dst = {(t[0], t[1]) for t in conf.tied_weights}
            for i, layer in enumerate(layers):
                key, sub = jax.random.split(key)
                p, s, shape = layer.init(sub, shape, dtype)
                if i in reinit or _lname(i) not in params:
                    new.params[_lname(i)] = p
                    new.state[_lname(i)] = s
                else:
                    # trained copies win; fresh leaves fill params the
                    # source never had as masters (a DROPPED tie's dst
                    # needs its W back) — but never resurrect a leaf a
                    # SURVIVING tie still materializes
                    merged = {k: v for k, v in p.items()
                              if (i, k) not in tied_dst}
                    merged.update(params[_lname(i)])
                    # dropped-tie dst params: trained tied value, not
                    # the fresh leaf
                    for (di, dn), val in dropped_fill.items():
                        if di == i:
                            merged[dn] = val
                    new.params[_lname(i)] = merged
                    new.state[_lname(i)] = state[_lname(i)]
                new._layer_shapes.append(shape)
            new._output_shape = shape
            new._build_optimizer()
            return new

    @staticmethod
    def builder(net: MultiLayerNetwork) -> "TransferLearning.Builder":
        return TransferLearning.Builder(net)


class TransferLearningHelper:
    """Featurize-once training on the unfrozen tail (reference
    TransferLearningHelper: featurize(DataSet) + fitFeaturized)."""

    def __init__(self, net: MultiLayerNetwork,
                 frozen_until: Optional[int] = None):
        if frozen_until is not None:
            net = (TransferLearning.builder(net)
                   .set_feature_extractor(frozen_until).build())
        self.net = net
        idx = -1
        for i, layer in enumerate(net.layers):
            if isinstance(layer, FrozenLayer):
                idx = i
        self._split = idx + 1        # first unfrozen layer index
        if self._split == 0:
            raise ValueError("network has no frozen prefix")
        # tail-only network with COPIES of the tail params — its jitted
        # step donates buffers, which must not delete the full net's
        # arrays (fit_featurized copies results back)
        import jax.numpy as jnp
        tail_conf = copy.deepcopy(net.conf)
        tail_conf.layers = net.layers[self._split:]
        # tie entries are layer-index based: reindex onto the tail; a
        # tie crossing the frozen/tail boundary has no tail-local
        # source and cannot be represented
        retied = []
        for di, dn, si, sn, tr in getattr(tail_conf, "tied_weights",
                                          []):
            if di >= self._split and si >= self._split:
                retied.append([di - self._split, dn,
                               si - self._split, sn, tr])
            elif di >= self._split or si >= self._split:
                raise ValueError(
                    f"tie_weights layer_{di}.{dn} <- layer_{si}.{sn} "
                    f"crosses the frozen/unfrozen split at "
                    f"{self._split}; freeze through both ends or "
                    f"neither")
        tail_conf.tied_weights = retied
        self._tail = MultiLayerNetwork(tail_conf)
        for i in range(self._split, len(net.layers)):
            self._tail.params[_lname(i - self._split)] = \
                jax.tree.map(jnp.array, net.params[_lname(i)])
            self._tail.state[_lname(i - self._split)] = \
                jax.tree.map(jnp.array, net.state[_lname(i)])
        self._tail._input_shape = net._layer_shapes[self._split - 1]
        self._tail._layer_shapes = net._layer_shapes[self._split:]
        self._tail._output_shape = net._output_shape
        self._tail._build_optimizer()

    def unfrozen_mln(self) -> MultiLayerNetwork:
        return self._tail

    def featurize(self, dataset):
        """Run the frozen prefix once; returns a DataSet of features
        (reference featurize)."""
        from deeplearning4j_tpu.data import DataSet

        feats = self.net.activate_selected_layers(
            0, self._split - 1, np.asarray(dataset.features))
        return DataSet(np.asarray(feats), dataset.labels)

    def fit_featurized(self, dataset_or_iter, epochs: int = 1):
        import jax.numpy as jnp

        self._tail.fit(dataset_or_iter, epochs=epochs)
        # propagate tail params back into the full net — as copies, so a
        # later fit_featurized's donation can't delete the full net's view
        for i in range(self._split, len(self.net.layers)):
            self.net.params[_lname(i)] = jax.tree.map(
                jnp.array, self._tail.params[_lname(i - self._split)])
            self.net.state[_lname(i)] = jax.tree.map(
                jnp.array, self._tail.state[_lname(i - self._split)])
        return self

    def output(self, x):
        return self.net.output(x)
