"""Convolution / pooling / spatial layers — channels-last (NHWC/NWC/NDHWC).

Reference classes (deeplearning4j-nn):
  org.deeplearning4j.nn.conf.layers.ConvolutionLayer (+ Convolution1DLayer,
  Convolution3D, Deconvolution2D, DepthwiseConvolution2D,
  SeparableConvolution2D), SubsamplingLayer (+1D/3D), GlobalPoolingLayer,
  Upsampling2D, ZeroPaddingLayer, Cropping2D, SpaceToDepthLayer; the
  cuDNN fast path (CudnnConvolutionHelper) is replaced by XLA's native
  convolution lowering, which autotunes for the MXU.

Padding modes mirror the reference ConvolutionMode: TRUNCATE ≈ VALID,
SAME = SAME. Kernels are stored [*spatial, in, out] (HWIO) so XLA needs
no transposes.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn import weights as winit


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    t = tuple(v)
    if len(t) != n:
        raise ValueError(f"expected {n}-tuple, got {t}")
    return t


def _conv_dims(n_spatial):
    # channels-last dimension_numbers for 1/2/3-D conv
    spec = {1: ("NWC", "WIO", "NWC"),
            2: ("NHWC", "HWIO", "NHWC"),
            3: ("NDHWC", "DHWIO", "NDHWC")}[n_spatial]
    return spec


def _out_spatial(size, k, s, d, padding):
    eff = (k - 1) * d + 1
    if padding == "SAME":
        return -(-size // s)
    return (size - eff) // s + 1


@register_layer
@dataclass
class ConvolutionLayer(Layer):
    """2-D convolution (reference ConvolutionLayer / cuDNN helper path)."""
    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: Sequence[int] = (3, 3)
    stride: Sequence[int] = (1, 1)
    padding: str = "VALID"            # reference ConvolutionMode
    dilation: Sequence[int] = (1, 1)
    has_bias: bool = True
    groups: int = 1
    _spatial: int = field(default=2, repr=False)

    def _kshape(self, c_in):
        k = _tup(self.kernel_size, self._spatial)
        return k + (c_in // self.groups, self.n_out)

    def init(self, key, input_shape, dtype=jnp.float32):
        c_in = self.n_in or input_shape[-1]
        params = {"W": winit.get(self.weight_init or "xavier")(
            key, self._kshape(c_in), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        k = _tup(self.kernel_size, self._spatial)
        s = _tup(self.stride, self._spatial)
        d = _tup(self.dilation, self._spatial)
        out_sp = tuple(_out_spatial(input_shape[i], k[i], s[i], d[i],
                                    self.padding)
                       for i in range(self._spatial))
        return params, {}, out_sp + (self.n_out,)

    def _conv(self, x, w):
        return lax.conv_general_dilated(
            x, w,
            window_strides=_tup(self.stride, self._spatial),
            padding=self.padding,
            rhs_dilation=_tup(self.dilation, self._spatial),
            dimension_numbers=_conv_dims(self._spatial),
            feature_group_count=self.groups)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        z = self._conv(x, params["W"])
        if self.has_bias:
            z = z + params["b"]
        y = self._act()(z)
        return self._maybe_dropout(y, train, rng), state


@register_layer
@dataclass
class Convolution1DLayer(ConvolutionLayer):
    """1-D conv over [B,T,C] (reference Convolution1DLayer)."""
    kernel_size: Sequence[int] = (3,)
    stride: Sequence[int] = (1,)
    dilation: Sequence[int] = (1,)
    _spatial: int = field(default=1, repr=False)

    def propagate_mask(self, mask, input_shape):
        if mask is None or self.padding == "SAME":
            return mask
        k = _tup(self.kernel_size, 1)[0]
        s = _tup(self.stride, 1)[0]
        d = _tup(self.dilation, 1)[0]
        t_out = _out_spatial(mask.shape[1], k, s, d, self.padding)
        return mask[:, :t_out * s:s]


@register_layer
@dataclass
class Convolution3DLayer(ConvolutionLayer):
    """3-D conv over [B,D,H,W,C] (reference Convolution3D)."""
    kernel_size: Sequence[int] = (3, 3, 3)
    stride: Sequence[int] = (1, 1, 1)
    dilation: Sequence[int] = (1, 1, 1)
    _spatial: int = field(default=3, repr=False)


@register_layer
@dataclass
class Deconvolution2DLayer(ConvolutionLayer):
    """Transposed conv (reference Deconvolution2D)."""

    def init(self, key, input_shape, dtype=jnp.float32):
        c_in = self.n_in or input_shape[-1]
        k = _tup(self.kernel_size, 2)
        s = _tup(self.stride, 2)
        params = {"W": winit.get(self.weight_init or "xavier")(
            key, k + (c_in, self.n_out), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        if self.padding == "SAME":
            out_sp = tuple(input_shape[i] * s[i] for i in range(2))
        else:
            out_sp = tuple((input_shape[i] - 1) * s[i] + k[i]
                           for i in range(2))
        return params, {}, out_sp + (self.n_out,)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        z = lax.conv_transpose(
            x, params["W"], strides=_tup(self.stride, 2),
            padding=self.padding, dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            z = z + params["b"]
        return self._act()(z), state


@register_layer
@dataclass
class DepthwiseConvolution2DLayer(ConvolutionLayer):
    """Depthwise conv (reference DepthwiseConvolution2D): depth_multiplier
    output channels per input channel via feature_group_count=C."""
    depth_multiplier: int = 1

    def init(self, key, input_shape, dtype=jnp.float32):
        c_in = self.n_in or input_shape[-1]
        self.n_out = c_in * self.depth_multiplier
        k = _tup(self.kernel_size, 2)
        params = {"W": winit.get(self.weight_init or "xavier")(
            key, k + (1, self.n_out), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        s = _tup(self.stride, 2)
        d = _tup(self.dilation, 2)
        out_sp = tuple(_out_spatial(input_shape[i], k[i], s[i], d[i],
                                    self.padding) for i in range(2))
        return params, {}, out_sp + (self.n_out,)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        z = lax.conv_general_dilated(
            x, params["W"], window_strides=_tup(self.stride, 2),
            padding=self.padding, rhs_dilation=_tup(self.dilation, 2),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=x.shape[-1])
        if self.has_bias:
            z = z + params["b"]
        return self._act()(z), state


@register_layer
@dataclass
class SeparableConvolution2DLayer(ConvolutionLayer):
    """Depthwise + pointwise (reference SeparableConvolution2D)."""
    depth_multiplier: int = 1

    def init(self, key, input_shape, dtype=jnp.float32):
        c_in = self.n_in or input_shape[-1]
        k = _tup(self.kernel_size, 2)
        kd, kp = jax.random.split(key)
        wi = winit.get(self.weight_init or "xavier")
        params = {
            "depthW": wi(kd, k + (1, c_in * self.depth_multiplier), dtype),
            "pointW": wi(kp, (1, 1, c_in * self.depth_multiplier,
                              self.n_out), dtype),
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        s = _tup(self.stride, 2)
        d = _tup(self.dilation, 2)
        out_sp = tuple(_out_spatial(input_shape[i], k[i], s[i], d[i],
                                    self.padding) for i in range(2))
        return params, {}, out_sp + (self.n_out,)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        z = lax.conv_general_dilated(
            x, params["depthW"], window_strides=_tup(self.stride, 2),
            padding=self.padding, rhs_dilation=_tup(self.dilation, 2),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=x.shape[-1])
        z = lax.conv_general_dilated(
            z, params["pointW"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            z = z + params["b"]
        return self._act()(z), state


@register_layer
@dataclass
class SubsamplingLayer(Layer):
    """2-D pooling (reference SubsamplingLayer, PoolingType MAX/AVG/PNORM).
    lax.reduce_window — XLA fuses with neighbors."""
    kernel_size: Sequence[int] = (2, 2)
    stride: Sequence[int] = (2, 2)
    padding: str = "VALID"
    pooling_type: str = "max"
    pnorm: int = 2
    _spatial: int = field(default=2, repr=False)

    def init(self, key, input_shape, dtype=jnp.float32):
        k = _tup(self.kernel_size, self._spatial)
        s = _tup(self.stride, self._spatial)
        out_sp = tuple(_out_spatial(input_shape[i], k[i], s[i], 1,
                                    self.padding)
                       for i in range(self._spatial))
        return {}, {}, out_sp + (input_shape[-1],)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        k = (1,) + _tup(self.kernel_size, self._spatial) + (1,)
        s = (1,) + _tup(self.stride, self._spatial) + (1,)
        pt = self.pooling_type.lower()
        if pt == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, k, s, self.padding)
        elif pt in ("avg", "mean"):
            total = lax.reduce_window(x, 0.0, lax.add, k, s, self.padding)
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, k, s, self.padding)
            y = total / cnt
        elif pt == "pnorm":
            p = float(self.pnorm)
            tot = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, k, s,
                                    self.padding)
            y = tot ** (1.0 / p)
        elif pt == "sum":
            y = lax.reduce_window(x, 0.0, lax.add, k, s, self.padding)
        else:
            raise ValueError(f"unknown pooling type {self.pooling_type!r}")
        return y, state

    def has_params(self):
        return False


@register_layer
@dataclass
class Subsampling1DLayer(SubsamplingLayer):
    kernel_size: Sequence[int] = (2,)
    stride: Sequence[int] = (2,)
    _spatial: int = field(default=1, repr=False)


@register_layer
@dataclass
class Subsampling3DLayer(SubsamplingLayer):
    kernel_size: Sequence[int] = (2, 2, 2)
    stride: Sequence[int] = (2, 2, 2)
    _spatial: int = field(default=3, repr=False)


@register_layer
@dataclass
class GlobalPoolingLayer(Layer):
    """Global pooling over all spatial/time axes (reference
    GlobalPoolingLayer; mask-aware for sequences)."""
    pooling_type: str = "max"
    pnorm: int = 2
    collapse_dimensions: bool = True

    def init(self, key, input_shape, dtype=jnp.float32):
        return {}, {}, (input_shape[-1],)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        axes = tuple(range(1, x.ndim - 1))
        pt = self.pooling_type.lower()
        if mask is not None and x.ndim == 3:
            m = mask[..., None].astype(x.dtype)
            if pt == "max":
                y = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1)
            elif pt in ("avg", "mean"):
                y = jnp.sum(x * m, axis=1) / jnp.maximum(
                    jnp.sum(m, axis=1), 1e-9)
            elif pt == "sum":
                y = jnp.sum(x * m, axis=1)
            elif pt == "pnorm":
                p = float(self.pnorm)
                y = (jnp.sum((jnp.abs(x) * m) ** p, axis=1)) ** (1 / p)
            else:
                raise ValueError(self.pooling_type)
            return y, state
        if pt == "max":
            y = jnp.max(x, axis=axes)
        elif pt in ("avg", "mean"):
            y = jnp.mean(x, axis=axes)
        elif pt == "sum":
            y = jnp.sum(x, axis=axes)
        elif pt == "pnorm":
            p = float(self.pnorm)
            y = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1 / p)
        else:
            raise ValueError(self.pooling_type)
        return y, state

    def propagate_mask(self, mask, input_shape):
        return None  # pooled away

    def has_params(self):
        return False


@register_layer
@dataclass
class Upsampling2DLayer(Layer):
    """Nearest-neighbor upsampling (reference Upsampling2D)."""
    size: Sequence[int] = (2, 2)

    def init(self, key, input_shape, dtype=jnp.float32):
        s = _tup(self.size, 2)
        return {}, {}, (input_shape[0] * s[0], input_shape[1] * s[1],
                        input_shape[2])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        s = _tup(self.size, 2)
        y = jnp.repeat(jnp.repeat(x, s[0], axis=1), s[1], axis=2)
        return y, state

    def has_params(self):
        return False


@register_layer
@dataclass
class ZeroPaddingLayer(Layer):
    """Spatial zero padding (reference ZeroPaddingLayer)."""
    padding: Sequence[int] = (1, 1, 1, 1)  # top,bottom,left,right

    def init(self, key, input_shape, dtype=jnp.float32):
        t, b, l, r = self.padding
        return {}, {}, (input_shape[0] + t + b, input_shape[1] + l + r,
                        input_shape[2])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t, b, l, r = self.padding
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state

    def has_params(self):
        return False


@register_layer
@dataclass
class CroppingLayer(Layer):
    """Spatial cropping (reference Cropping2D)."""
    cropping: Sequence[int] = (0, 0, 0, 0)

    def init(self, key, input_shape, dtype=jnp.float32):
        t, b, l, r = self.cropping
        return {}, {}, (input_shape[0] - t - b, input_shape[1] - l - r,
                        input_shape[2])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t, b, l, r = self.cropping
        h, w = x.shape[1], x.shape[2]
        return x[:, t:h - b, l:w - r, :], state

    def has_params(self):
        return False


@register_layer
@dataclass
class SpaceToDepthLayer(Layer):
    """Space-to-depth (reference SpaceToDepthLayer)."""
    block_size: int = 2

    def init(self, key, input_shape, dtype=jnp.float32):
        b = self.block_size
        h, w, c = input_shape
        return {}, {}, (h // b, w // b, c * b * b)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        b = self.block_size
        n, h, w, c = x.shape
        y = x.reshape(n, h // b, b, w // b, b, c)
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // b, w // b,
                                                  c * b * b)
        return y, state

    def has_params(self):
        return False


@register_layer
@dataclass
class DepthToSpaceLayer(Layer):
    """Inverse of SpaceToDepth (reference libnd4j depth_to_space op)."""
    block_size: int = 2

    def init(self, key, input_shape, dtype=jnp.float32):
        b = self.block_size
        h, w, c = input_shape
        return {}, {}, (h * b, w * b, c // (b * b))

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        b = self.block_size
        n, h, w, c = x.shape
        y = x.reshape(n, h, w, b, b, c // (b * b))
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(n, h * b, w * b,
                                                  c // (b * b))
        return y, state

    def has_params(self):
        return False


@register_layer
@dataclass
class Upsampling1DLayer(Layer):
    """Nearest-neighbor upsampling over time (reference Upsampling1D),
    [B, T, C]."""
    size: int = 2

    def init(self, key, input_shape, dtype=jnp.float32):
        t = input_shape[0]
        return {}, {}, (None if t is None else t * self.size,
                        input_shape[1])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.repeat(x, self.size, axis=1), state

    def propagate_mask(self, mask, input_shape):
        # time axis grows T -> T*size; stretch the mask with it
        return None if mask is None else jnp.repeat(mask, self.size,
                                                    axis=1)

    def has_params(self):
        return False


@register_layer
@dataclass
class Upsampling3DLayer(Layer):
    """Nearest-neighbor upsampling (reference Upsampling3D),
    [B, D, H, W, C]."""
    size: Sequence[int] = (2, 2, 2)

    def init(self, key, input_shape, dtype=jnp.float32):
        s = _tup(self.size, 3)
        return {}, {}, (input_shape[0] * s[0], input_shape[1] * s[1],
                        input_shape[2] * s[2], input_shape[3])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        s = _tup(self.size, 3)
        for ax, r in zip((1, 2, 3), s):
            x = jnp.repeat(x, r, axis=ax)
        return x, state

    def has_params(self):
        return False
