"""Special-purpose layers.

Reference: org.deeplearning4j.nn.conf.layers.variational.
VariationalAutoencoder, AutoEncoder, CenterLossOutputLayer,
misc.FrozenLayer, util.IdentityLayer / LambdaLayer (samediff),
CapsuleLayer, PReLULayer.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import weights as winit


@register_layer
@dataclass
class AutoEncoder(Layer):
    """Denoising autoencoder layer (reference AutoEncoder): forward pass
    encodes; pretraining reconstructs with tied-ish decode weights."""
    n_in: Optional[int] = None
    n_out: int = 0
    corruption_level: float = 0.3

    def init(self, key, input_shape, dtype=jnp.float32):
        n_in = self.n_in or input_shape[-1]
        kW, = jax.random.split(key, 1)
        wi = winit.get(self.weight_init or "xavier")
        params = {"W": wi(kW, (n_in, self.n_out), dtype),
                  "b": jnp.zeros((self.n_out,), dtype),
                  "vb": jnp.zeros((n_in,), dtype)}  # visible bias (decode)
        return params, {}, (self.n_out,)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if train and rng is not None and self.corruption_level > 0:
            keep = jax.random.bernoulli(rng, 1 - self.corruption_level,
                                        x.shape)
            x = jnp.where(keep, x, 0.0).astype(x.dtype)
        return self._act("sigmoid")(x @ params["W"] + params["b"]), state

    def reconstruct(self, params, h):
        return self._act("sigmoid")(h @ params["W"].T + params["vb"])


@register_layer
@dataclass
class VariationalAutoencoder(Layer):
    """VAE (reference variational.VariationalAutoencoder): gaussian
    reparameterization; ``elbo_loss`` gives the pretraining objective."""
    n_in: Optional[int] = None
    n_out: int = 0                      # latent size
    encoder_layer_sizes: Sequence[int] = (256,)
    decoder_layer_sizes: Sequence[int] = (256,)

    def init(self, key, input_shape, dtype=jnp.float32):
        n_in = self.n_in or input_shape[-1]
        wi = winit.get(self.weight_init or "xavier")
        params = {"enc": [], "dec": []}
        sizes = [n_in, *self.encoder_layer_sizes]
        keys = jax.random.split(key, len(sizes) + len(
            self.decoder_layer_sizes) + 4)
        ki = iter(keys)
        for a, b in zip(sizes[:-1], sizes[1:]):
            params["enc"].append({"W": wi(next(ki), (a, b), dtype),
                                  "b": jnp.zeros((b,), dtype)})
        h = sizes[-1]
        params["mu"] = {"W": wi(next(ki), (h, self.n_out), dtype),
                        "b": jnp.zeros((self.n_out,), dtype)}
        params["logvar"] = {"W": wi(next(ki), (h, self.n_out), dtype),
                            "b": jnp.zeros((self.n_out,), dtype)}
        dsizes = [self.n_out, *self.decoder_layer_sizes]
        for a, b in zip(dsizes[:-1], dsizes[1:]):
            params["dec"].append({"W": wi(next(ki), (a, b), dtype),
                                  "b": jnp.zeros((b,), dtype)})
        params["out"] = {"W": wi(next(ki), (dsizes[-1], n_in), dtype),
                         "b": jnp.zeros((n_in,), dtype)}
        return params, {}, (self.n_out,)

    def _encode(self, params, x):
        h = x
        act = self._act("leakyrelu")
        for lyr in params["enc"]:
            h = act(h @ lyr["W"] + lyr["b"])
        mu = h @ params["mu"]["W"] + params["mu"]["b"]
        logvar = h @ params["logvar"]["W"] + params["logvar"]["b"]
        return mu, logvar

    def _decode(self, params, z):
        h = z
        act = self._act("leakyrelu")
        for lyr in params["dec"]:
            h = act(h @ lyr["W"] + lyr["b"])
        return h @ params["out"]["W"] + params["out"]["b"]

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        mu, logvar = self._encode(params, x)
        if train and rng is not None:
            z = mu + jnp.exp(0.5 * logvar) * jax.random.normal(
                rng, mu.shape, mu.dtype)
        else:
            z = mu
        return z, state

    def elbo_loss(self, params, x, rng):
        mu, logvar = self._encode(params, x)
        z = mu + jnp.exp(0.5 * logvar) * jax.random.normal(
            rng, mu.shape, mu.dtype)
        recon = self._decode(params, z)
        rec = jnp.mean(jnp.sum(jnp.square(recon - x), axis=-1))
        kl = -0.5 * jnp.mean(jnp.sum(
            1 + logvar - jnp.square(mu) - jnp.exp(logvar), axis=-1))
        return rec + kl


@register_layer
@dataclass
class CenterLossOutputLayer(OutputLayer):
    """Output layer with center loss (reference CenterLossOutputLayer):
    pulls features toward per-class centers. Centers live in state."""
    alpha: float = 0.05
    lambda_: float = 2e-4

    def init(self, key, input_shape, dtype=jnp.float32):
        params, state, out = super().init(key, input_shape, dtype)
        n_in = self.n_in or input_shape[-1]
        state = dict(state)
        state["centers"] = jnp.zeros((self.n_out, n_in), dtype)
        return params, state, out

    def center_loss(self, state, features, label_idx):
        centers = state["centers"][label_idx]
        return 0.5 * self.lambda_ * jnp.mean(
            jnp.sum(jnp.square(features - centers), axis=-1))

    def update_centers(self, state, features, label_idx):
        centers = state["centers"]
        diff = centers[label_idx] - features
        counts = jax.ops.segment_sum(
            jnp.ones_like(label_idx, jnp.float32), label_idx,
            centers.shape[0]) + 1.0
        delta = jax.ops.segment_sum(diff, label_idx, centers.shape[0])
        new = centers - self.alpha * delta / counts[:, None]
        return {**state, "centers": new}


@register_layer
@dataclass
class FrozenLayer(Layer):
    """Wrapper excluding the underlying layer's params from training
    (reference FrozenLayer; used by transfer learning)."""
    underlying: Optional[Layer] = None

    def init(self, key, input_shape, dtype=jnp.float32):
        return self.underlying.init(key, input_shape, dtype)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # train=False for the wrapped layer: frozen layers run in
        # inference mode (reference semantics, e.g. BN uses running stats)
        return self.underlying.apply(params, state, x, train=False,
                                     rng=rng, mask=mask)

    def propagate_mask(self, mask, input_shape):
        return self.underlying.propagate_mask(mask, input_shape)

    @property
    def trainable_(self):
        return False


@register_layer
@dataclass
class LambdaLayer(Layer):
    """Arbitrary paramless function layer (reference samediff Lambda
    layers / SameDiffLayer simple case). Not JSON-serializable unless
    ``fn`` is re-attached after load."""
    fn: Optional[Callable] = None
    output_shape_fn: Optional[Callable] = None

    def init(self, key, input_shape, dtype=jnp.float32):
        out = (self.output_shape_fn(input_shape) if self.output_shape_fn
               else tuple(input_shape))
        return {}, {}, out

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.fn(x), state

    def to_dict(self):
        d = super().to_dict()
        d["fn"] = None
        d["output_shape_fn"] = None
        return d

    def has_params(self):
        return False


@register_layer
@dataclass
class PReLULayer(Layer):
    """Parametric ReLU with learned per-feature alpha (reference
    PReLULayer)."""
    def init(self, key, input_shape, dtype=jnp.float32):
        return ({"alpha": jnp.full((input_shape[-1],), 0.25, dtype)},
                {}, tuple(input_shape))

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.where(x >= 0, x, params["alpha"] * x), state


@register_layer
@dataclass
class CapsuleLayer(Layer):
    """Capsule layer with dynamic routing (reference CapsuleLayer,
    capsnet family). Routing iterations unrolled (static count) for jit."""
    n_in: Optional[int] = None
    capsules: int = 10
    capsule_dim: int = 16
    routings: int = 3
    input_capsules: Optional[int] = None
    input_capsule_dim: Optional[int] = None

    def init(self, key, input_shape, dtype=jnp.float32):
        ic, icd = input_shape[-2], input_shape[-1]
        wi = winit.get(self.weight_init or "xavier")
        params = {"W": wi(key, (ic, self.capsules * self.capsule_dim, icd),
                          dtype)}
        self.input_capsules, self.input_capsule_dim = ic, icd
        return params, {}, (self.capsules, self.capsule_dim)

    @staticmethod
    def _squash(v, axis=-1):
        n2 = jnp.sum(jnp.square(v), axis=axis, keepdims=True)
        return (n2 / (1 + n2)) * v / jnp.sqrt(n2 + 1e-9)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # x: [B, IC, ICD] -> predictions u_hat [B, IC, C, CD]
        u_hat = jnp.einsum("bid,icd->bic", x, params["W"]).reshape(
            x.shape[0], x.shape[1], self.capsules, self.capsule_dim)
        b_logits = jnp.zeros(u_hat.shape[:3], u_hat.dtype)
        for _ in range(self.routings):
            c = jax.nn.softmax(b_logits, axis=-1)
            s = jnp.einsum("bic,bicd->bcd", c, u_hat)
            v = self._squash(s)
            b_logits = b_logits + jnp.einsum("bicd,bcd->bic", u_hat, v)
        return v, state
