"""Recurrent layers — [B, T, F] layout, lax.scan time loops.

Reference classes (deeplearning4j-nn):
  org.deeplearning4j.nn.conf.layers.LSTM / GravesLSTM /
  GravesBidirectionalLSTM (→ Bidirectional wrapper here), SimpleRnn,
  recurrent.Bidirectional, recurrent.LastTimeStep, util.MaskZeroLayer,
  RnnOutputLayer / RnnLossLayer; math in
  org.deeplearning4j.nn.layers.recurrent.LSTMHelpers (+CudnnLSTMHelper).

TPU design: the input projection for ALL timesteps is one large batched
matmul ([B*T, F] @ [F, 4H] — lands on the MXU); only the recurrent
h @ U part runs inside ``lax.scan``. Masked steps hold state (h,c carry
through) and emit zeros, matching the reference's mask semantics.
Stored-state inference (reference ``rnnTimeStep`` /
``rnnActivateUsingStoredState`` for truncated BPTT) is supported via the
``initial_state``/returned-state pair.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn.layers.core import (DenseLayer, LossLayer,
                                               OutputLayer)
from deeplearning4j_tpu.nn import weights as winit
from deeplearning4j_tpu.ops import activations

# moderate scan unrolling: fewer XLA while-loop iterations
# (each costs HBM carry round-trips) without exploding compile
# time — ~1.8x on BPTT through a 512-wide LSTM on v5e
_SCAN_UNROLL = 4



class BaseRecurrentLayer(Layer):
    """Common recurrent machinery: returns (y[B,T,H], state with
    'h' (+'c') final carries for tBPTT)."""

    def rnn_state_shapes(self, hidden):
        raise NotImplementedError


@register_layer
@dataclass
class LSTM(BaseRecurrentLayer):
    """LSTM without peepholes (reference LSTM — the cuDNN-compatible
    variant). Gate order [i, f, o, g] like the reference LSTMHelpers."""
    n_in: Optional[int] = None
    n_out: int = 0
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"
    peephole: bool = field(default=False, repr=False)

    def init(self, key, input_shape, dtype=jnp.float32):
        n_in = self.n_in or input_shape[-1]
        h = self.n_out
        kW, kU, kP = jax.random.split(key, 3)
        wi = winit.get(self.weight_init or "xavier")
        params = {
            "W": wi(kW, (n_in, 4 * h), dtype),   # input → gates
            "U": wi(kU, (h, 4 * h), dtype),      # recurrent → gates
            "b": jnp.concatenate([
                jnp.zeros((h,), dtype),
                jnp.full((h,), self.forget_gate_bias_init, dtype),
                jnp.zeros((2 * h,), dtype)]),
        }
        if self.peephole:
            params["P"] = jnp.zeros((3, h), dtype)  # pi, pf, po
        t = input_shape[0] if len(input_shape) == 2 else None
        return params, {}, (t, h)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None,
              initial_state=None):
        b, t, _ = x.shape
        h = self.n_out
        dt = x.dtype
        gate_act = activations.get(self.gate_activation)
        act = self._act("tanh")
        if initial_state is None:
            h0 = jnp.zeros((b, h), dt)
            c0 = jnp.zeros((b, h), dt)
        else:
            h0, c0 = initial_state["h"], initial_state["c"]

        # One big MXU matmul for every timestep's input projection.
        xg = (x.reshape(b * t, -1) @ params["W"] + params["b"]).reshape(
            b, t, 4 * h)
        xg = jnp.swapaxes(xg, 0, 1)  # [T, B, 4H] scan-major
        m = (jnp.ones((t, b, 1), dt) if mask is None
             else jnp.swapaxes(mask, 0, 1)[..., None].astype(dt))

        U = params["U"]
        P = params.get("P")

        def step(carry, inp):
            hp, cp = carry
            g, mt = inp
            z = g + hp @ U
            zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
            if P is not None:  # Graves peepholes on i, f from c_{t-1}
                zi = zi + cp * P[0]
                zf = zf + cp * P[1]
            i = gate_act(zi)
            f = gate_act(zf)
            gg = act(zg)
            c = f * cp + i * gg
            if P is not None:  # peephole on o from c_t
                zo = zo + c * P[2]
            o = gate_act(zo)
            hh = o * act(c)
            # masked steps: hold state, emit zeros
            c = mt * c + (1 - mt) * cp
            hn = mt * hh + (1 - mt) * hp
            return (hn, c), hh * mt

        (hT, cT), ys = lax.scan(step, (h0, c0), (xg, m),
                                unroll=_SCAN_UNROLL)
        y = jnp.swapaxes(ys, 0, 1)
        y = self._maybe_dropout(y, train, rng)
        return y, {"h": hT, "c": cT}


@register_layer
@dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (reference GravesLSTM, per
    Graves 2013)."""
    peephole: bool = field(default=True, repr=False)


@register_layer
@dataclass
class SimpleRnn(BaseRecurrentLayer):
    """Elman RNN: h_t = act(x W + h_{t-1} U + b) (reference SimpleRnn)."""
    n_in: Optional[int] = None
    n_out: int = 0

    def init(self, key, input_shape, dtype=jnp.float32):
        n_in = self.n_in or input_shape[-1]
        kW, kU = jax.random.split(key)
        wi = winit.get(self.weight_init or "xavier")
        params = {"W": wi(kW, (n_in, self.n_out), dtype),
                  "U": wi(kU, (self.n_out, self.n_out), dtype),
                  "b": jnp.zeros((self.n_out,), dtype)}
        t = input_shape[0] if len(input_shape) == 2 else None
        return params, {}, (t, self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None,
              initial_state=None):
        b, t, _ = x.shape
        dt = x.dtype
        act = self._act("tanh")
        h0 = (jnp.zeros((b, self.n_out), dt) if initial_state is None
              else initial_state["h"])
        xg = jnp.swapaxes(x @ params["W"] + params["b"], 0, 1)
        m = (jnp.ones((t, b, 1), dt) if mask is None
             else jnp.swapaxes(mask, 0, 1)[..., None].astype(dt))
        U = params["U"]

        def step(hp, inp):
            g, mt = inp
            hh = act(g + hp @ U)
            hn = mt * hh + (1 - mt) * hp
            return hn, hh * mt

        hT, ys = lax.scan(step, h0, (xg, m),
                          unroll=_SCAN_UNROLL)
        y = jnp.swapaxes(ys, 0, 1)
        return self._maybe_dropout(y, train, rng), {"h": hT}


@register_layer
@dataclass
class GRU(BaseRecurrentLayer):
    """GRU (reference libnd4j ``gruCell`` op / samediff GRU).

    ``reset_after=False`` (default, the paper/libnd4j formulation):
    ``n = act(x·Wn + (r ⊙ h)·Un + bn)``. ``reset_after=True`` (the
    cuDNN-compatible variant, Keras default): ``n = act(x·Wn +
    r ⊙ (h·Un + rbn))`` with a separate recurrent bias ``rb``.
    """
    n_in: Optional[int] = None
    n_out: int = 0
    gate_activation: str = "sigmoid"
    reset_after: bool = False

    def init(self, key, input_shape, dtype=jnp.float32):
        n_in = self.n_in or input_shape[-1]
        h = self.n_out
        kW, kU = jax.random.split(key)
        wi = winit.get(self.weight_init or "xavier")
        params = {"W": wi(kW, (n_in, 3 * h), dtype),
                  "U": wi(kU, (h, 3 * h), dtype),
                  "b": jnp.zeros((3 * h,), dtype)}
        if self.reset_after:
            params["rb"] = jnp.zeros((3 * h,), dtype)
        t = input_shape[0] if len(input_shape) == 2 else None
        return params, {}, (t, h)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None,
              initial_state=None):
        b, t, _ = x.shape
        h = self.n_out
        dt = x.dtype
        gact = activations.get(self.gate_activation)
        act = self._act("tanh")
        h0 = (jnp.zeros((b, h), dt) if initial_state is None
              else initial_state["h"])
        xg = jnp.swapaxes(
            (x.reshape(b * t, -1) @ params["W"] + params["b"]).reshape(
                b, t, 3 * h), 0, 1)
        m = (jnp.ones((t, b, 1), dt) if mask is None
             else jnp.swapaxes(mask, 0, 1)[..., None].astype(dt))
        U = params["U"]

        rb = params["rb"] if self.reset_after else None
        Urz, Un = U[:, :2 * h], U[:, 2 * h:]

        def step(hp, inp):
            g, mt = inp
            xr, xz, xn = jnp.split(g, 3, axis=-1)
            if self.reset_after:
                hg = hp @ U + rb
                hr, hz, hn_ = jnp.split(hg, 3, axis=-1)
                r = gact(xr + hr)
                z = gact(xz + hz)
                n = act(xn + r * hn_)
            else:
                hr, hz = jnp.split(hp @ Urz, 2, axis=-1)
                r = gact(xr + hr)
                z = gact(xz + hz)
                n = act(xn + (r * hp) @ Un)
            hh = (1 - z) * n + z * hp
            hn = mt * hh + (1 - mt) * hp
            return hn, hh * mt

        hT, ys = lax.scan(step, h0, (xg, m),
                          unroll=_SCAN_UNROLL)
        y = jnp.swapaxes(ys, 0, 1)
        return self._maybe_dropout(y, train, rng), {"h": hT}


@register_layer
@dataclass
class Bidirectional(Layer):
    """Bidirectional wrapper (reference recurrent.Bidirectional; covers
    GravesBidirectionalLSTM as Bidirectional(GravesLSTM)). Modes: concat,
    add, mul, average (reference Bidirectional.Mode)."""
    fwd: Optional[Layer] = None
    mode: str = "concat"

    def init(self, key, input_shape, dtype=jnp.float32):
        kf, kb = jax.random.split(key)
        pf, sf, of = self.fwd.init(kf, input_shape, dtype)
        pb, sb, _ = self.fwd.init(kb, input_shape, dtype)
        out = of
        if self.mode == "concat":
            out = of[:-1] + (of[-1] * 2,)
        return {"fwd": pf, "bwd": pb}, {"fwd": sf, "bwd": sb}, out

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # apply() is pure given params — the same config drives both
        # directions with their own param subtrees.
        r1, r2 = (jax.random.split(rng) if rng is not None else (None, None))
        yf, sf = self.fwd.apply(params["fwd"], state.get("fwd", {}), x,
                                train=train, rng=r1, mask=mask)
        # mask-aware time reversal: reverse only the valid prefix
        if mask is not None:
            lengths = jnp.sum(mask.astype(jnp.int32), axis=1)
            xr = _reverse_padded(x, lengths)
        else:
            xr = jnp.flip(x, axis=1)
        yb, sb = self.fwd.apply(params["bwd"], state.get("bwd", {}), xr,
                                train=train, rng=r2, mask=mask)
        # re-align backward outputs to forward time — unless the wrapped
        # layer collapsed the time axis (e.g. LastTimeStep)
        if yb.ndim >= 3:
            if mask is not None:
                yb = _reverse_padded(yb, lengths)
            else:
                yb = jnp.flip(yb, axis=1)
        if self.mode == "concat":
            y = jnp.concatenate([yf, yb], axis=-1)
        elif self.mode == "add":
            y = yf + yb
        elif self.mode == "mul":
            y = yf * yb
        elif self.mode == "average":
            y = 0.5 * (yf + yb)
        else:
            raise ValueError(f"unknown Bidirectional mode {self.mode!r}")
        return y, {"fwd": sf, "bwd": sb}


def _reverse_padded(x, lengths):
    """Reverse each sequence's valid prefix, keeping padding in place."""
    t = x.shape[1]
    idx = jnp.arange(t)[None, :]                        # [1,T]
    rev = lengths[:, None] - 1 - idx                    # valid reversed pos
    gather = jnp.where(idx < lengths[:, None], rev, idx)
    return jnp.take_along_axis(
        x, gather[..., None].astype(jnp.int32), axis=1)


@register_layer
@dataclass
class LastTimeStep(Layer):
    """Wraps a recurrent layer, emits only the last *valid* timestep
    (reference recurrent.LastTimeStep)."""
    underlying: Optional[Layer] = None

    def init(self, key, input_shape, dtype=jnp.float32):
        p, s, out = self.underlying.init(key, input_shape, dtype)
        return p, s, (out[-1],)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        y, s = self.underlying.apply(params, state, x, train=train, rng=rng,
                                     mask=mask)
        if mask is not None:
            lengths = jnp.sum(mask.astype(jnp.int32), axis=1)
            idx = jnp.maximum(lengths - 1, 0)
            out = jnp.take_along_axis(
                y, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        else:
            out = y[:, -1]
        return out, s

    def propagate_mask(self, mask, input_shape):
        return None


@register_layer
@dataclass
class MaskZeroLayer(Layer):
    """Derives a time mask from input rows equal to ``mask_value`` and
    applies the underlying layer with it (reference util.MaskZeroLayer)."""
    underlying: Optional[Layer] = None
    mask_value: float = 0.0

    def init(self, key, input_shape, dtype=jnp.float32):
        return self.underlying.init(key, input_shape, dtype)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        derived = jnp.any(x != self.mask_value, axis=-1).astype(x.dtype)
        if mask is not None:
            derived = derived * mask
        return self.underlying.apply(params, state, x, train=train, rng=rng,
                                     mask=derived)


@register_layer
@dataclass
class TimeDistributed(Layer):
    """Applies a feed-forward layer independently per timestep
    (reference misc.TimeDistributed): folds time into batch around one
    big batched op."""
    underlying: Optional[Layer] = None

    def init(self, key, input_shape, dtype=jnp.float32):
        p, s, out = self.underlying.init(key, input_shape[1:], dtype)
        return p, s, (input_shape[0],) + out

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        b, t = x.shape[:2]
        flat = x.reshape((b * t,) + x.shape[2:])
        y, s = self.underlying.apply(params, state, flat, train=train,
                                     rng=rng)
        return y.reshape((b, t) + y.shape[1:]), s


@register_layer
@dataclass
class RnnOutputLayer(OutputLayer):
    """Per-timestep dense + loss head over [B,T,F] (reference
    RnnOutputLayer)."""

    def init(self, key, input_shape, dtype=jnp.float32):
        n_in = self.n_in or input_shape[-1]
        params, state, _ = DenseLayer.init(self, key, (n_in,), dtype)
        t = input_shape[0] if len(input_shape) == 2 else None
        return params, state, (t, self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return self._act()(z), state


@register_layer
@dataclass
class RnnLossLayer(LossLayer):
    """Loss-only over sequences (reference RnnLossLayer)."""


@register_layer
@dataclass
class GravesBidirectionalLSTM(Bidirectional):
    """Reference GravesBidirectionalLSTM — forward and backward
    GravesLSTM passes SUMMED (reference semantics: output width stays
    ``n_out``, unlike Bidirectional's default concat)."""
    n_in: Optional[int] = None
    n_out: int = 0
    mode: str = "add"

    def __post_init__(self):
        if self.fwd is None:
            self.fwd = GravesLSTM(
                n_in=self.n_in, n_out=self.n_out,
                activation=self.activation,
                weight_init=self.weight_init, dropout=self.dropout,
                l1=self.l1, l2=self.l2, bias_init=self.bias_init)


@register_layer
@dataclass
class ConvLSTM2D(Layer):
    """Convolutional LSTM over [B, T, H, W, C] (Keras ConvLSTM2D /
    reference keras-import ``KerasConvLSTM2D``): every gate is a conv —
    the input path convolves each frame, the recurrent path convolves
    the hidden state (stride 1, SAME so spatial dims persist).

    TPU design: the input convolution for ALL timesteps is ONE batched
    conv ([B*T, H, W, C] — lands on the MXU); only the recurrent conv
    runs inside ``lax.scan``. Gate packing follows Keras ([i, f, c, o]
    along the last kernel axis) so imported weights map 1:1.
    """
    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: tuple = (3, 3)
    stride: tuple = (1, 1)
    padding: str = "VALID"
    gate_activation: str = "hardsigmoid_keras"
    return_sequences: bool = True
    forget_gate_bias_init: float = 1.0

    def _out_hw(self, h, w):
        kh, kw = self.kernel_size
        sh, sw = self.stride
        if self.padding.upper() == "SAME":
            return -(-h // sh), -(-w // sw)
        return (h - kh) // sh + 1, (w - kw) // sw + 1

    def init(self, key, input_shape, dtype=jnp.float32):
        t, h, w, c = input_shape
        c = self.n_in or c
        f = self.n_out
        kh, kw = self.kernel_size
        kx, kh_ = jax.random.split(key)
        wi = winit.get(self.weight_init or "xavier")
        bias = jnp.concatenate([
            jnp.zeros((f,), dtype),
            jnp.full((f,), self.forget_gate_bias_init, dtype),
            jnp.zeros((2 * f,), dtype)])
        params = {"Wx": wi(kx, (kh, kw, c, 4 * f), dtype),
                  "Wh": wi(kh_, (kh, kw, f, 4 * f), dtype),
                  "b": bias}
        oh, ow = self._out_hw(h, w)
        out = (t, oh, ow, f) if self.return_sequences else (oh, ow, f)
        return params, {}, out

    def apply(self, params, state, x, *, train=False, rng=None,
              mask=None):
        b, t, h, w, c = x.shape
        f = self.n_out
        dn = ("NHWC", "HWIO", "NHWC")
        gate = activations.get(self.gate_activation)
        act = self._act("tanh")
        # one batched conv for every frame's input projection
        xg = lax.conv_general_dilated(
            x.reshape(b * t, h, w, c), params["Wx"],
            window_strides=self.stride, padding=self.padding.upper(),
            dimension_numbers=dn) + params["b"]
        oh, ow = xg.shape[1:3]
        xg = xg.reshape(b, t, oh, ow, 4 * f).swapaxes(0, 1)
        Wh = params["Wh"]

        def step(carry, g):
            hp, cp = carry
            z = g + lax.conv_general_dilated(
                hp, Wh, window_strides=(1, 1), padding="SAME",
                dimension_numbers=dn)
            zi, zf, zc, zo = jnp.split(z, 4, axis=-1)  # Keras order
            i, fg, o = gate(zi), gate(zf), gate(zo)
            cn = fg * cp + i * act(zc)
            hn = o * act(cn)
            return (hn, cn), hn

        zeros = jnp.zeros((b, oh, ow, f), x.dtype)
        (hT, _), ys = lax.scan(step, (zeros, zeros), xg,
                               unroll=_SCAN_UNROLL)
        if not self.return_sequences:
            return hT, state
        return jnp.swapaxes(ys, 0, 1), state
