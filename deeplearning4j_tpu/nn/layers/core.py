"""Core feed-forward layers.

Reference classes (deeplearning4j-nn):
  org.deeplearning4j.nn.conf.layers.DenseLayer / OutputLayer / LossLayer /
  ActivationLayer / DropoutLayer / EmbeddingLayer / EmbeddingSequenceLayer /
  ElementWiseMultiplicationLayer / BatchNormalization /
  LocalResponseNormalization; impls under org.deeplearning4j.nn.layers.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn import weights as winit
from deeplearning4j_tpu.ops import losses as losses_mod


@register_layer
@dataclass
class DenseLayer(Layer):
    """Fully connected layer (reference DenseLayer; cuDNN-free matmul —
    lands directly on the MXU). Supports the reference's ``hasLayerNorm``
    option (DenseLayer.Builder.hasLayerNorm)."""
    n_in: Optional[int] = None
    n_out: int = 0
    has_layer_norm: bool = False
    has_bias: bool = True

    def init(self, key, input_shape, dtype=jnp.float32):
        n_in = self.n_in or int(math.prod(input_shape))
        kW, = jax.random.split(key, 1)
        params = {"W": winit.get(self.weight_init or "xavier")(
            kW, (n_in, self.n_out), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        if self.has_layer_norm:
            params["g"] = jnp.ones((self.n_out,), dtype)
        return params, {}, (self.n_out,)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        z = x @ params["W"]
        if self.has_layer_norm:
            mu = jnp.mean(z, axis=-1, keepdims=True)
            var = jnp.var(z, axis=-1, keepdims=True)
            z = params["g"] * (z - mu) / jnp.sqrt(var + 1e-5)
        if self.has_bias:
            z = z + params["b"]
        y = self._act()(z)
        return self._maybe_dropout(y, train, rng), state


@register_layer
@dataclass
class OutputLayer(DenseLayer):
    """Dense + loss head (reference OutputLayer extends BaseOutputLayer).

    ``loss`` names a function in ``ops.losses``; scoring happens in the
    network's train step, where the loss is applied to this layer's
    activations (with from_logits fusion when activation is softmax —
    see MultiLayerNetwork._loss_of).
    """
    loss: str = "mcxent"


@register_layer
@dataclass
class LossLayer(Layer):
    """Loss-only layer, no params (reference LossLayer)."""
    loss: str = "mse"

    def init(self, key, input_shape, dtype=jnp.float32):
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self._act()(x), state

    def has_params(self):
        return False


@register_layer
@dataclass
class ActivationLayer(Layer):
    """Stateless activation (reference ActivationLayer)."""

    def init(self, key, input_shape, dtype=jnp.float32):
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self._act()(x), state

    def has_params(self):
        return False


@register_layer
@dataclass
class DropoutLayer(Layer):
    """Standalone dropout (reference DropoutLayer). ``dropout`` is the
    drop probability; inverted dropout (scale at train time)."""

    def __post_init__(self):
        if self.dropout is None:
            self.dropout = 0.5

    def init(self, key, input_shape, dtype=jnp.float32):
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self._maybe_dropout(x, train, rng), state

    def has_params(self):
        return False


@register_layer
@dataclass
class EmbeddingLayer(Layer):
    """Int index -> dense vector (reference EmbeddingLayer; one index per
    example). A gather — XLA lowers to a dynamic-slice, TPU-friendly."""
    n_in: Optional[int] = None     # vocab size
    n_out: int = 0
    has_bias: bool = False

    def init(self, key, input_shape, dtype=jnp.float32):
        params = {"W": winit.get(self.weight_init or "xavier")(
            key, (self.n_in, self.n_out), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}, (self.n_out,)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        y = params["W"][idx]
        if self.has_bias:
            y = y + params["b"]
        return self._act()(y), state


@register_layer
@dataclass
class EmbeddingSequenceLayer(EmbeddingLayer):
    """Sequence of indices [B,T] -> [B,T,F] (reference
    EmbeddingSequenceLayer)."""
    input_length: Optional[int] = None

    def init(self, key, input_shape, dtype=jnp.float32):
        params, state, _ = super().init(key, input_shape, dtype)
        t = self.input_length or (input_shape[0] if input_shape else None)
        return params, state, (t, self.n_out)


@register_layer
@dataclass
class ElementWiseMultiplicationLayer(Layer):
    """out = activation(in ⊙ w + b) (reference
    ElementWiseMultiplicationLayer)."""
    n_out: int = 0

    def init(self, key, input_shape, dtype=jnp.float32):
        n = self.n_out or input_shape[-1]
        params = {"W": jnp.ones((n,), dtype),
                  "b": jnp.full((n,), self.bias_init, dtype)}
        return params, {}, (n,)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self._act()(x * params["W"] + params["b"]), state


@register_layer
@dataclass
class BatchNormalization(Layer):
    """Batch norm over the trailing feature/channel axis (reference
    BatchNormalization + CudnnBatchNormalizationHelper; here one fused
    XLA graph, running stats carried in ``state``)."""
    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False

    def init(self, key, input_shape, dtype=jnp.float32):
        c = input_shape[-1]
        params = {} if self.lock_gamma_beta else {
            "gamma": jnp.ones((c,), dtype), "beta": jnp.zeros((c,), dtype)}
        state = {"mean": jnp.zeros((c,), dtype),
                 "var": jnp.ones((c,), dtype)}
        return params, state, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        axes = tuple(range(x.ndim - 1))
        sdt = jnp.promote_types(x.dtype, jnp.float32)  # f64 stays f64
        if train:
            xf = x.astype(sdt)
            mu = jnp.mean(xf, axis=axes)
            if x.dtype in (jnp.bfloat16, jnp.float16):
                # one-pass batch stats: E[x] and E[x²] reduce together
                # in a single fused multi-output reduction (jnp.var
                # walks x twice and materialises x-mu — ~25% of a
                # ResNet-50 step went to those reductions). Safe here:
                # a half-precision input with |mean|≫std carries no var
                # information in EITHER formulation, and the squares
                # accumulate in fp32.
                var = (jnp.mean(jnp.square(xf), axis=axes)
                       - jnp.square(mu))
                var = jnp.maximum(var, 0.0)
            else:
                # full precision: shifted two-pass, immune to the
                # catastrophic cancellation of E[x²]−E[x]²
                var = jnp.mean(jnp.square(xf - mu), axis=axes)
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mu,
                "var": self.decay * state["var"] + (1 - self.decay) * var,
            }
        else:
            mu = state["mean"].astype(sdt)
            var = state["var"].astype(sdt)
            new_state = state
        # fold into one fused multiply-add over the big tensor:
        # y = a·x + b with per-channel a, b
        inv = jax.lax.rsqrt(var + self.eps)
        if not self.lock_gamma_beta:
            inv = inv * params["gamma"].astype(sdt)
            b = params["beta"].astype(sdt) - mu * inv
        else:
            b = -mu * inv
        y = x * inv.astype(x.dtype) + b.astype(x.dtype)
        return self._act()(y), new_state

    def has_params(self):
        return not self.lock_gamma_beta


@register_layer
@dataclass
class LayerNormalization(Layer):
    """Layer norm over the trailing axis. The reference exposes this as
    DenseLayer.hasLayerNorm / SameDiff ``standardize``; standalone layer
    added for the transformer stack."""
    eps: float = 1e-5

    def init(self, key, input_shape, dtype=jnp.float32):
        c = input_shape[-1]
        return ({"gamma": jnp.ones((c,), dtype),
                 "beta": jnp.zeros((c,), dtype)}, {}, tuple(input_shape))

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # platform-helper dispatch (ops/fused_norms.py): fused Pallas
        # LayerNorm on TPU, the exact pre-existing XLA expression
        # otherwise (gate-off programs byte-identical)
        from deeplearning4j_tpu.ops import fused_norms
        return fused_norms.layer_norm(x, params["gamma"],
                                      params["beta"],
                                      eps=self.eps), state


#: default RMSNorm epsilon — zoo/gpt.py's KV-cache decode re-derives
#: the norm inline and MUST use the same value (kept in one place)
RMSNORM_EPS = 1e-6


@register_layer
@dataclass
class RMSNorm(Layer):
    """Root-mean-square norm over the trailing axis (no mean
    subtraction, no bias) — the modern-LM normalisation the causal
    transformer stack uses. No reference counterpart (its transformer
    support predates RMSNorm); provided for the native LM family."""
    eps: float = RMSNORM_EPS

    def init(self, key, input_shape, dtype=jnp.float32):
        c = input_shape[-1]
        return {"gamma": jnp.ones((c,), dtype)}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # platform-helper dispatch (ops/fused_norms.py): fused Pallas
        # RMSNorm on TPU, the exact pre-existing XLA expression
        # otherwise (gate-off programs byte-identical)
        from deeplearning4j_tpu.ops import fused_norms
        return fused_norms.rms_norm(x, params["gamma"],
                                    eps=self.eps), state


@register_layer
@dataclass
class LocalResponseNormalization(Layer):
    """LRN across channels (reference LocalResponseNormalization —
    AlexNet-era). Channels-last."""
    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def init(self, key, input_shape, dtype=jnp.float32):
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        sq = jnp.square(x)
        half = self.n // 2
        pads = [(0, 0)] * (x.ndim - 1) + [(half, half)]
        padded = jnp.pad(sq, pads)
        # sliding-window sum over channel axis via cumsum difference
        cs = jnp.cumsum(padded, axis=-1)
        zeros = jnp.zeros_like(cs[..., :1])
        cs = jnp.concatenate([zeros, cs], axis=-1)
        win = cs[..., self.n:] - cs[..., :-self.n]
        denom = jnp.power(self.k + self.alpha * win, self.beta)
        return x / denom, state

    def has_params(self):
        return False


@register_layer
@dataclass
class CnnLossLayer(LossLayer):
    """Per-position loss over [B, H, W, C] feature maps (reference
    CnnLossLayer — segmentation-style heads where every spatial
    position carries a label). Loss machinery is the network's
    (labels shaped like the activations); this layer applies the
    activation only."""


@register_layer
@dataclass
class Cnn3DLossLayer(LossLayer):
    """Reference Cnn3DLossLayer — [B, D, H, W, C] per-position loss."""
