"""Attention layers.

Reference: org.deeplearning4j.nn.conf.layers.SelfAttentionLayer /
LearnedSelfAttentionLayer / RecurrentAttentionLayer (deeplearning4j-nn)
over libnd4j ops ``dot_product_attention`` /
``multi_head_dot_product_attention``; plus the transformer-era stack
(MultiHeadAttention, TransformerEncoderBlock, positional embeddings) the
BERT-base BASELINE config needs. Long-context ring attention lives in
``parallel.ring_attention``.

All shapes [B, T, F]; mask [B, T] (key mask). Attention math is
``jax.nn.dot_product_attention`` — XLA fuses it into flash-attention-
style blocks on TPU.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn.layers.core import LayerNormalization
from deeplearning4j_tpu.nn import weights as winit


def _split_heads(x, n_heads):
    b, t, f = x.shape
    return x.reshape(b, t, n_heads, f // n_heads)


def _merge_heads(x):
    b, t, h, d = x.shape
    return x.reshape(b, t, h * d)


def rotary_embedding(x, theta: float = 10000.0, offset=0):
    """Rotary position embedding (RoPE) on [B, T, H, D] (D even):
    HALF-SPLIT pairing (GPT-NeoX convention — feature i rotates with
    feature i + D/2, NOT the interleaved (i, i+1) GPT-J convention;
    permute Wq/Wk columns when importing interleaved-RoPE weights).
    Scores depend only on RELATIVE position — the modern long-context
    positional scheme. ``offset`` shifts the position index (KV-cache
    decoding)."""
    b, t, h, d = x.shape
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    pos = offset + jnp.arange(t, dtype=jnp.float32)
    ang = pos[:, None] * freqs[None, :]            # [T, D/2]
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


def repeat_kv_heads(k, n_heads: int):
    """Grouped-query attention: broadcast ``n_kv`` key/value heads to
    ``n_heads`` query heads ([B, T, n_kv, D] → [B, T, n_heads, D])."""
    n_kv = k.shape[2]
    if n_kv == n_heads:
        return k
    if n_heads % n_kv:
        raise ValueError(f"n_heads={n_heads} not divisible by "
                         f"n_kv_heads={n_kv}")
    return jnp.repeat(k, n_heads // n_kv, axis=2)


def _use_flash(q, k, causal: bool = False) -> bool:
    """Platform-helper gate: route to the Pallas flash kernel when the
    KEY sequence is long enough for the blockwise kernel to win (O(Tk)
    memory, skipped dead blocks), including cross-attention — Tq may
    differ. Tiny-Tq shapes (a scan step's single query, learned-query
    pooling) stay on the einsum: their score tile is already O(Tk) and
    the kernel would pad Tq to a full 128-row MXU block per launch.
    Causal with Tq > Tk stays on the einsum too: its leading Tq−Tk
    rows have NO live keys, and the two paths define that degenerate
    row differently (kernel: zeros; einsum: uniform average).
    Threshold via DL4J_TPU_FLASH_MIN_T (crossover measured on v5e,
    tools/flash_crossover.py). ``DL4J_TPU_KERNEL_FORCE`` skips the
    platform/size gates (interpret-mode kernel on CPU) so CI can
    exercise the dispatch decision itself; the SEMANTIC refusals —
    causal Tq > Tk, float64 — hold either way."""
    from deeplearning4j_tpu.environment import get_flag
    semantic_ok = (not (causal and q.shape[1] > k.shape[1])
                   and q.dtype != jnp.float64)
    if get_flag("DL4J_TPU_KERNEL_FORCE"):
        return semantic_ok
    return (semantic_ok
            and k.shape[1] >= get_flag("DL4J_TPU_FLASH_MIN_T")
            and q.shape[1] >= 128
            and jax.default_backend() == "tpu")


def scaled_dot_attention(q, k, v, mask=None, causal=False):
    """q,k,v: [B, T, H, D] (head axis 2); ``k``/``v`` may carry fewer
    heads (GQA); Tq and Tk may differ (causal is then END-ALIGNED:
    query i attends keys ≤ i + Tk − Tq). mask: [B, Tk] key mask.

    Explicit einsum+softmax (not jax.nn.dot_product_attention, which is
    not exact in float64 — breaks gradient checking). Platform-helper
    dispatch (the reference's cuDNN-helper pattern, SURVEY §2.3): on
    TPU with long key sequences the Pallas flash kernel is used instead
    — O(Tk) memory, 1.2-1.7x faster than the einsum at T>=4k, and
    GQA-native (one kv block read per head group).
    """
    d = q.shape[-1]
    if _use_flash(q, k, causal):
        # masked sequences take the flash path too (per-example key
        # mask operand in the kernel) — every padded-batch NLP workload
        # stays O(T) memory instead of falling back to the [T,T] einsum
        from deeplearning4j_tpu.ops.pallas_kernels import flash_attention
        return flash_attention(q, k, v, causal=causal, mask=mask)
    k = repeat_kv_heads(k, q.shape[2])
    v = repeat_kv_heads(v, q.shape[2])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
        jnp.asarray(d, q.dtype))
    neg = jnp.asarray(-1e30 if q.dtype == jnp.float64 else -1e9, q.dtype)
    if mask is not None:
        logits = jnp.where(mask[:, None, None, :] > 0, logits, neg)
    if causal:
        tq, tk = logits.shape[-2:]
        cm = jnp.tril(jnp.ones((tq, tk), bool), tk - tq)
        logits = jnp.where(cm, logits, neg)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


@register_layer
@dataclass
class MultiHeadAttention(Layer):
    """Self multi-head attention projection block (reference
    multi_head_dot_product_attention op + AttentionVertex).

    ``sequence_parallel``: ``"ring"`` | ``"zigzag_ring"`` |
    ``"ulysses"`` | ``None`` — when an ambient
    ``parallel.distributed_context`` is active, the attention runs
    sequence-parallel over its mesh (ring ppermute, load-balanced
    zigzag ring, or all-to-all head swap); outside a context it falls
    back to local attention, so the same model config runs single- and
    multi-chip. Entering/exiting the context invalidates the owning
    net's jitted traces, so the decision is never stale.
    """
    n_in: Optional[int] = None
    n_out: int = 0
    n_heads: int = 1
    causal: bool = False
    project_out: bool = True
    sequence_parallel: Optional[str] = None
    n_kv_heads: Optional[int] = None   # grouped-query attention
    rope: bool = False                 # rotary position embeddings
    rope_theta: float = 10000.0

    _SP_MODES = (None, "ring", "ulysses", "zigzag_ring")

    def _attend(self, q, k, v, mask):
        """``k``/``v`` may carry fewer heads than ``q`` (GQA): the
        ring paths keep the SMALL kv on the wire and the flash kernels
        read one kv block per head group; only Ulysses (head-axis
        all-to-all) needs the broadcast."""
        if self.sequence_parallel not in self._SP_MODES:
            # reject typos even single-chip, where no context is active
            raise ValueError(
                f"unknown sequence_parallel mode "
                f"{self.sequence_parallel!r} (ring|ulysses|zigzag_ring)")
        n_heads = q.shape[2]
        if self.sequence_parallel:
            from deeplearning4j_tpu.parallel.mesh import active_context
            ctx = active_context()
            if ctx is not None:
                if self.sequence_parallel == "ring":
                    from deeplearning4j_tpu.parallel.ring_attention \
                        import ring_self_attention
                    return ring_self_attention(
                        q, k, v, ctx.mesh, axis_name=ctx.axis_name,
                        mask=mask, causal=self.causal,
                        batch_axis=getattr(ctx, "batch_axis", None),
                        head_axis=getattr(ctx, "head_axis", None))
                if self.sequence_parallel == "ulysses":
                    from deeplearning4j_tpu.parallel.ulysses import \
                        ulysses_self_attention
                    return ulysses_self_attention(
                        q, repeat_kv_heads(k, n_heads),
                        repeat_kv_heads(v, n_heads), ctx.mesh,
                        axis_name=ctx.axis_name,
                        mask=mask, causal=self.causal)
                if self.sequence_parallel == "zigzag_ring":
                    # load-balanced causal ring; tokens permuted into
                    # zigzag layout around the call (pre-permute the
                    # DATA once instead for production pipelines)
                    from deeplearning4j_tpu.parallel.ring_attention \
                        import (zigzag_permute,
                                zigzag_ring_self_attention,
                                zigzag_unpermute)
                    if not self.causal:
                        raise ValueError("zigzag_ring is causal-only")
                    n = ctx.mesh.shape[ctx.axis_name]
                    zmask = (None if mask is None
                             else zigzag_permute(mask, n, axis=1))
                    o = zigzag_ring_self_attention(
                        zigzag_permute(q, n), zigzag_permute(k, n),
                        zigzag_permute(v, n), ctx.mesh,
                        axis_name=ctx.axis_name, mask=zmask,
                        batch_axis=getattr(ctx, "batch_axis", None),
                        head_axis=getattr(ctx, "head_axis", None))
                    return zigzag_unpermute(o, n)
        return scaled_dot_attention(q, k, v, mask, self.causal)

    def init(self, key, input_shape, dtype=jnp.float32):
        n_in = self.n_in or input_shape[-1]
        n_out = self.n_out or n_in
        if n_out % self.n_heads:
            raise ValueError(f"n_out={n_out} not divisible by "
                             f"n_heads={self.n_heads}")
        n_kv = self.n_kv_heads or self.n_heads
        if self.n_heads % n_kv:
            raise ValueError(f"n_heads={self.n_heads} not divisible "
                             f"by n_kv_heads={n_kv}")
        kv_out = (n_out // self.n_heads) * n_kv
        wi = winit.get(self.weight_init or "xavier")
        kq, kk, kv_, ko = jax.random.split(key, 4)
        params = {"Wq": wi(kq, (n_in, n_out), dtype),
                  "Wk": wi(kk, (n_in, kv_out), dtype),
                  "Wv": wi(kv_, (n_in, kv_out), dtype)}
        if self.project_out:
            params["Wo"] = wi(ko, (n_out, n_out), dtype)
            params["bo"] = jnp.zeros((n_out,), dtype)
        t = input_shape[0]
        return params, {}, (t, n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        n_kv = self.n_kv_heads or self.n_heads
        q = _split_heads(x @ params["Wq"], self.n_heads)
        k = _split_heads(x @ params["Wk"], n_kv)
        v = _split_heads(x @ params["Wv"], n_kv)
        if self.rope:
            q = rotary_embedding(q, self.rope_theta)
            k = rotary_embedding(k, self.rope_theta)
        o = _merge_heads(self._attend(q, k, v, mask))
        if self.project_out:
            o = o @ params["Wo"] + params["bo"]
        if mask is not None:
            o = o * mask[..., None].astype(o.dtype)
        return self._maybe_dropout(self._act()(o), train, rng), state


@register_layer
@dataclass
class SelfAttentionLayer(MultiHeadAttention):
    """Reference SelfAttentionLayer: self-attention, output per timestep."""


@register_layer
@dataclass
class LearnedSelfAttentionLayer(Layer):
    """Attention with ``n_queries`` learned query vectors (reference
    LearnedSelfAttentionLayer) — pools [B,T,F] to [B,Q,F_out]."""
    n_in: Optional[int] = None
    n_out: int = 0
    n_heads: int = 1
    n_queries: int = 1

    def init(self, key, input_shape, dtype=jnp.float32):
        n_in = self.n_in or input_shape[-1]
        n_out = self.n_out or n_in
        wi = winit.get(self.weight_init or "xavier")
        kq, kk, kv, kp = jax.random.split(key, 4)
        params = {"Q": wi(kq, (self.n_queries, n_out), dtype),
                  "Wk": wi(kk, (n_in, n_out), dtype),
                  "Wv": wi(kv, (n_in, n_out), dtype)}
        return params, {}, (self.n_queries, n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        b = x.shape[0]
        q = jnp.broadcast_to(params["Q"][None], (b,) + params["Q"].shape)
        q = _split_heads(q, self.n_heads)
        k = _split_heads(x @ params["Wk"], self.n_heads)
        v = _split_heads(x @ params["Wv"], self.n_heads)
        o = _merge_heads(scaled_dot_attention(q, k, v, mask))
        return self._act()(o), state

    def propagate_mask(self, mask, input_shape):
        return None  # fixed n_queries output, fully valid


@register_layer
@dataclass
class PositionalEmbeddingLayer(Layer):
    """Learned positional embeddings added to [B,T,F] (BERT-style)."""
    max_len: int = 512

    def init(self, key, input_shape, dtype=jnp.float32):
        t, f = input_shape
        params = {"pos": jax.random.normal(
            key, (self.max_len, f), dtype) * 0.02}
        return params, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t = x.shape[1]
        return x + params["pos"][None, :t, :], state


@register_layer
@dataclass
class TransformerEncoderBlock(Layer):
    """Pre-LN transformer encoder block: MHA + MLP with residuals.

    The reference has no transformer block layer (its BERT support comes
    through TF import, SURVEY §3.4) — provided natively here since the
    BASELINE BERT config demands it.
    """
    n_in: Optional[int] = None
    n_heads: int = 8
    ffn_mult: float = 4
    causal: bool = False
    sequence_parallel: Optional[str] = None

    def init(self, key, input_shape, dtype=jnp.float32):
        f = self.n_in = self.n_in or input_shape[-1]
        wi = winit.get(self.weight_init or "xavier")
        ks = jax.random.split(key, 6)
        self._mha = MultiHeadAttention(
            n_in=f, n_out=f, n_heads=self.n_heads, causal=self.causal,
            sequence_parallel=self.sequence_parallel)
        self._ln1 = LayerNormalization()
        self._ln2 = LayerNormalization()
        pa, _, _ = self._mha.init(ks[0], input_shape, dtype)
        p1, _, _ = self._ln1.init(ks[1], input_shape, dtype)
        p2, _, _ = self._ln2.init(ks[2], input_shape, dtype)
        hid = int(round(f * self.ffn_mult))
        params = {"mha": pa, "ln1": p1, "ln2": p2,
                  "W1": wi(ks[3], (f, hid), dtype),
                  "b1": jnp.zeros((hid,), dtype),
                  "W2": wi(ks[4], (hid, f), dtype),
                  "b2": jnp.zeros((f,), dtype)}
        return params, {}, tuple(input_shape)

    def _subs(self, input_shape=None):
        f = self.n_in
        if not hasattr(self, "_mha"):
            self._mha = MultiHeadAttention(
                n_in=f, n_out=f, n_heads=self.n_heads,
                causal=self.causal,
                sequence_parallel=self.sequence_parallel)
            self._ln1 = LayerNormalization()
            self._ln2 = LayerNormalization()

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        self._subs()
        r1, r2 = (jax.random.split(rng) if rng is not None else (None, None))
        h, _ = self._ln1.apply(params["ln1"], {}, x)
        a, _ = self._mha.apply(params["mha"], {}, h, train=train, rng=r1,
                               mask=mask)
        x = x + a
        h, _ = self._ln2.apply(params["ln2"], {}, x)
        h = jax.nn.gelu(h @ params["W1"] + params["b1"])
        h = h @ params["W2"] + params["b2"]
        x = x + self._maybe_dropout(h, train, r2)
        return x, state


@register_layer
@dataclass
class TransformerDecoderBlock(Layer):
    """Pre-RMSNorm causal decoder block (modern-LM style): grouped-
    query attention with rotary embeddings + SwiGLU MLP, residuals
    around both. The reference has no decoder-only transformer (its
    LM story is char-RNN + imported BERT); this is the native causal-LM
    building block, sequence-parallel-ready via ``sequence_parallel``.

    ``remat=True`` wraps the block in ``jax.checkpoint``: activations
    inside the block are recomputed during backward instead of stored —
    the standard FLOPs-for-HBM trade that makes deep long-context
    stacks fit (peak activation memory drops from O(layers·T·F) to
    O(T·F) + per-block recompute).
    """
    n_in: Optional[int] = None
    n_heads: int = 8
    n_kv_heads: Optional[int] = None
    # float allowed: 8/3 is the LLaMA convention that makes a SwiGLU
    # block parameter-match a classic 4x two-matrix MLP
    ffn_mult: float = 4
    rope_theta: float = 10000.0
    sequence_parallel: Optional[str] = None
    remat: bool = False

    def _subs(self):
        if not hasattr(self, "_mha"):
            from deeplearning4j_tpu.nn.layers.core import RMSNorm
            f = self.n_in
            self._mha = MultiHeadAttention(
                n_in=f, n_out=f, n_heads=self.n_heads,
                n_kv_heads=self.n_kv_heads, causal=True, rope=True,
                rope_theta=self.rope_theta,
                sequence_parallel=self.sequence_parallel)
            self._ln1 = RMSNorm()
            self._ln2 = RMSNorm()

    def init(self, key, input_shape, dtype=jnp.float32):
        f = self.n_in = self.n_in or input_shape[-1]
        self._subs()
        wi = winit.get(self.weight_init or "xavier")
        ks = jax.random.split(key, 6)
        pa, _, _ = self._mha.init(ks[0], input_shape, dtype)
        p1, _, _ = self._ln1.init(ks[1], input_shape, dtype)
        p2, _, _ = self._ln2.init(ks[2], input_shape, dtype)
        hid = int(round(f * self.ffn_mult))
        params = {"mha": pa, "ln1": p1, "ln2": p2,
                  # SwiGLU: (silu(x W_gate) ⊙ x W_up) W_down
                  "Wg": wi(ks[3], (f, hid), dtype),
                  "Wu": wi(ks[4], (f, hid), dtype),
                  "Wd": wi(ks[5], (hid, f), dtype)}
        return params, {}, tuple(input_shape)

    def _body(self, params, x, mask, train, rng):
        from deeplearning4j_tpu.ops import fused_norms
        r1, r2 = (jax.random.split(rng) if rng is not None
                  else (None, None))
        h, _ = self._ln1.apply(params["ln1"], {}, x)
        a, _ = self._mha.apply(params["mha"], {}, h, train=train,
                               rng=r1, mask=mask)
        # residual add + RMSNorm as ONE fused epilogue on TPU
        # (ops/fused_norms.py); gate-off runs the exact pre-existing
        # add-then-norm pair
        h, x = fused_norms.add_rms_norm(x, a, params["ln2"]["gamma"],
                                        eps=self._ln2.eps)
        h = jax.nn.silu(h @ params["Wg"]) * (h @ params["Wu"])
        return x + self._maybe_dropout(h @ params["Wd"], train, r2)

    def apply(self, params, state, x, *, train=False, rng=None,
              mask=None):
        self._subs()
        if self.remat:
            fn = jax.checkpoint(
                lambda p, x: self._body(p, x, mask, train, rng))
            return fn(params, x), state
        return self._body(params, x, mask, train, rng), state


@register_layer
@dataclass
class ClsTokenPoolLayer(Layer):
    """[B,T,F] -> [B,F]: select the first (CLS) token, optionally through
    a tanh pooler dense (BERT's pooler). The reference has no such layer
    — its BERT path pools inside the imported TF graph (SURVEY §3.4)."""
    n_out: int = 0                 # 0: no pooler dense, raw CLS vector
    pooler: bool = False

    def init(self, key, input_shape, dtype=jnp.float32):
        t, f = input_shape
        if self.n_out and not self.pooler:
            raise ValueError("ClsTokenPoolLayer: n_out requires "
                             "pooler=True (no projection otherwise)")
        if self.pooler:
            n = self.n_out or f
            wi = winit.get(self.weight_init or "xavier")
            params = {"W": wi(key, (f, n), dtype),
                      "b": jnp.zeros((n,), dtype)}
            return params, {}, (n,)
        return {}, {}, (f,)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        cls = x[:, 0, :]
        if self.pooler:
            cls = jnp.tanh(cls @ params["W"] + params["b"])
        return cls, state

    def propagate_mask(self, mask, out_len=None):
        return None                # sequence axis is gone


@register_layer
@dataclass
class RecurrentAttentionLayer(Layer):
    """Reference RecurrentAttentionLayer: a SimpleRnn whose step also
    attends over the WHOLE input sequence with the previous hidden
    state as query —
    ``h_t = act(W·x_t + U·h_{t-1} + Wo·attn(h_{t-1}, X, X) + b)``.
    K/V projections are one big MXU matmul outside the ``lax.scan``;
    only the query/attend/update runs per step."""
    n_in: Optional[int] = None
    n_out: int = 0
    n_heads: int = 1

    def init(self, key, input_shape, dtype=jnp.float32):
        n_in = self.n_in or input_shape[-1]
        h = self.n_out
        if h % self.n_heads:
            raise ValueError(f"n_out={h} % n_heads={self.n_heads} != 0")
        wi = winit.get(self.weight_init or "xavier")
        ks = jax.random.split(key, 6)
        params = {"W": wi(ks[0], (n_in, h), dtype),
                  "U": wi(ks[1], (h, h), dtype),
                  "Wq": wi(ks[2], (h, h), dtype),
                  "Wk": wi(ks[3], (n_in, h), dtype),
                  "Wv": wi(ks[4], (n_in, h), dtype),
                  "Wo": wi(ks[5], (h, h), dtype),
                  "b": jnp.zeros((h,), dtype)}
        t = input_shape[0]
        return params, {}, (t, h)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        b, t, _ = x.shape
        h = self.n_out
        nh = self.n_heads
        hd = h // nh
        dt = x.dtype
        act = self._act("tanh")
        xg = jnp.swapaxes(x @ params["W"] + params["b"], 0, 1)  # [T,B,H]
        k = (x @ params["Wk"]).reshape(b, t, nh, hd)
        v = (x @ params["Wv"]).reshape(b, t, nh, hd)
        m = (jnp.ones((t, b, 1), dt) if mask is None
             else jnp.swapaxes(mask, 0, 1)[..., None].astype(dt))
        U, Wq, Wo = params["U"], params["Wq"], params["Wo"]

        def step(hp, inp):
            g, mt = inp
            q = (hp @ Wq).reshape(b, 1, nh, hd)
            a = scaled_dot_attention(q, k, v, mask).reshape(b, h)
            hh = act(g + hp @ U + a @ Wo)
            # masked steps hold state, emit zeros (module convention)
            hn = mt * hh + (1 - mt) * hp
            return hn, hh * mt

        h0 = jnp.zeros((b, h), dt)
        _, ys = jax.lax.scan(step, h0, (xg, m))
        y = jnp.swapaxes(ys, 0, 1)
        return self._maybe_dropout(y, train, rng), state
