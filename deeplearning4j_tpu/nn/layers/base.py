"""Layer base class + registry.

Reference: ``org.deeplearning4j.nn.conf.layers.Layer`` bean hierarchy and
``org.deeplearning4j.nn.api.Layer`` runtime interface, unified: one
dataclass per layer with config (serialized to JSON), shape inference
(``init``) and a pure apply used under jit.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import activations

_LAYER_REGISTRY: Dict[str, type] = {}


def register_layer(cls):
    """Class decorator adding the layer to the serialization registry."""
    _LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_from_dict(d: Dict[str, Any]) -> "Layer":
    d = dict(d)
    kind = d.pop("@class")
    if kind not in _LAYER_REGISTRY:
        raise ValueError(f"Unknown layer class {kind!r}")
    cls = _LAYER_REGISTRY[kind]
    nested = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for k, v in d.items():
        if k in nested:
            # Re-hydrate nested beans (wrapped layers, constraints,
            # weight noise)
            if isinstance(v, dict) and "@class" in v:
                from deeplearning4j_tpu.nn import constraints as cmod
                cname = v["@class"]
                if cname in cmod._CONSTRAINTS:
                    v = cmod.BaseConstraint.from_dict(v)
                elif cname in cmod._NOISES:
                    v = cmod.BaseWeightNoise.from_dict(v)
                else:
                    v = layer_from_dict(v)
            elif (k == "constraints" and isinstance(v, list)):
                from deeplearning4j_tpu.nn import constraints as cmod
                v = [cmod.BaseConstraint.from_dict(c)
                     if isinstance(c, dict) else c for c in v]
            kwargs[k] = v
    return cls(**kwargs)


@dataclass
class Layer:
    """Base config bean + runtime for all layers.

    Subclasses implement:
      init(key, input_shape, dtype) -> (params, state, output_shape)
      apply(params, state, x, *, train, rng, mask) -> (y, new_state)

    ``input_shape``/``output_shape`` exclude the batch dimension.
    ``params`` are trainable leaves; ``state`` is non-trainable (e.g.
    batch-norm running stats). ``mask`` is [B, T] for sequence data.
    """
    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    bias_init: float = 0.0
    l1: Optional[float] = None
    l2: Optional[float] = None
    weight_decay: Optional[float] = None
    dropout: Optional[float] = None          # keep-prob complement: drop rate
    updater: Optional[Any] = None            # per-layer updater override
    learning_rate: Optional[float] = None    # per-layer LR override
    trainable: bool = True
    constraints: Optional[list] = None       # post-update param constraints
    weight_noise: Optional[Any] = None       # train-time weight noise

    # ---- serialization ---------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        out = {"@class": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Layer):
                v = v.to_dict()
            elif isinstance(v, list):
                v = [e.to_dict() if hasattr(e, "to_dict") else e
                     for e in v]
            elif hasattr(v, "to_dict") and not isinstance(v, type):
                v = v.to_dict()
            out[f.name] = v
        return out

    # ---- runtime ---------------------------------------------------------
    def init(self, key, input_shape, dtype=jnp.float32):
        raise NotImplementedError

    def apply(self, params, state, x, *, train: bool = False, rng=None,
              mask=None):
        raise NotImplementedError

    def propagate_mask(self, mask, input_shape):
        """Transform an incoming [B,T] mask for downstream layers.

        Reference: Layer.feedForwardMaskArray. Default: unchanged.
        """
        return mask

    # ---- helpers ---------------------------------------------------------
    def _act(self, default="identity"):
        return activations.get(self.activation or default)

    def _maybe_dropout(self, x, train, rng):
        if not train or not self.dropout or self.dropout <= 0.0:
            return x
        if rng is None:
            raise ValueError(
                f"Layer {self.name or type(self).__name__} has dropout "
                "but no rng was supplied to apply()")
        keep = 1.0 - self.dropout
        m = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(m, x / keep, 0.0).astype(x.dtype)

    def has_params(self) -> bool:
        return True

    def n_params(self, params) -> int:
        return sum(int(jnp.size(p)) for p in jax.tree.leaves(params))
