"""Layer implementations — reference: ``org.deeplearning4j.nn.conf.layers``
(config beans) + ``org.deeplearning4j.nn.layers.**`` (impls), ~60 layers.

Here config and impl are one class per layer: a serializable dataclass
bean with ``init(...)`` (parameter creation + shape inference, the
reference's ``getOutputType``/``initializer``) and a pure functional
``apply(...)`` used under jit (the reference's ``activate``). Gradients
come from jax autodiff — no ``backpropGradient`` methods.

Layout conventions are TPU-first: channels-last everywhere (NHWC / NWC /
[B,T,F] for sequences) — the reference's NCHW/[B,F,T] layouts are a CUDA
idiom; XLA on TPU prefers trailing feature dims.
"""
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer, layer_from_dict
from deeplearning4j_tpu.nn.layers.core import (
    DenseLayer, OutputLayer, LossLayer, ActivationLayer, DropoutLayer,
    EmbeddingLayer, EmbeddingSequenceLayer, ElementWiseMultiplicationLayer,
    BatchNormalization, LayerNormalization, LocalResponseNormalization,
    CnnLossLayer, Cnn3DLossLayer, RMSNorm,
)
from deeplearning4j_tpu.nn.layers.conv import (
    ConvolutionLayer, Convolution1DLayer, Convolution3DLayer,
    Deconvolution2DLayer, DepthwiseConvolution2DLayer,
    SeparableConvolution2DLayer, SubsamplingLayer, Subsampling1DLayer,
    Subsampling3DLayer, GlobalPoolingLayer, Upsampling2DLayer,
    ZeroPaddingLayer, CroppingLayer, SpaceToDepthLayer, DepthToSpaceLayer,
    Upsampling1DLayer, Upsampling3DLayer,
)
from deeplearning4j_tpu.nn.layers.recurrent import (
    LSTM, GravesLSTM, SimpleRnn, GRU, Bidirectional, LastTimeStep,
    RnnOutputLayer, RnnLossLayer, MaskZeroLayer, TimeDistributed,
    GravesBidirectionalLSTM, ConvLSTM2D,
)
from deeplearning4j_tpu.nn.layers.attention import (
    SelfAttentionLayer, LearnedSelfAttentionLayer, MultiHeadAttention,
    TransformerEncoderBlock, PositionalEmbeddingLayer, ClsTokenPoolLayer,
    RecurrentAttentionLayer, TransformerDecoderBlock,
)
from deeplearning4j_tpu.nn.layers.special import (
    AutoEncoder, VariationalAutoencoder, CenterLossOutputLayer,
    FrozenLayer, LambdaLayer, CapsuleLayer, PReLULayer,
)
from deeplearning4j_tpu.nn.layers.objdetect import Yolo2OutputLayer
from deeplearning4j_tpu.nn.layers.extra import (
    LocallyConnected1DLayer, LocallyConnected2DLayer, PrimaryCapsules,
    CapsuleStrengthLayer, OCNNOutputLayer, FrozenLayerWithBackprop,
    MaskLayer, RepeatVector, Cropping1DLayer, Cropping3DLayer,
    ZeroPadding1DLayer, ZeroPadding3DLayer, Deconvolution3DLayer,
    GaussianNoiseLayer, GaussianDropoutLayer,
    SameDiffLayer, SameDiffOutputLayer,
)

__all__ = [n for n in dir() if not n.startswith("_")]
