"""Object-detection output layer (reference:
``org.deeplearning4j.nn.layers.objdetect.Yolo2OutputLayer`` +
``conf.layers.objdetect.Yolo2OutputLayer`` config bean and
``YoloUtils``).

TPU-native layout: activations are NHWC ``[B, H, W, A*(5+C)]`` (the
reference uses NCHW ``[B, A*(5+C), H, W]``). Labels are
``[B, H, W, 4+C]``: per grid cell a box (cx, cy, w, h) in *grid units*
plus a one-hot class; cells with no object have w == h == 0. The
responsible anchor per object cell is chosen by max IOU of (w, h)
against the anchor priors — the YOLOv2 training rule.

The whole loss is one fused XLA program inside the network's jitted
train step (the reference computes it op-by-op through libnd4j).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


def _iou_wh(wh1, wh2):
    """IOU of two boxes sharing a center, given (w, h) only."""
    inter = jnp.minimum(wh1[..., 0], wh2[..., 0]) * \
        jnp.minimum(wh1[..., 1], wh2[..., 1])
    union = wh1[..., 0] * wh1[..., 1] + wh2[..., 0] * wh2[..., 1] - inter
    return inter / jnp.maximum(union, 1e-9)


def _iou_xywh(xy1, wh1, xy2, wh2):
    """Full IOU of center-format boxes (positions included)."""
    lo = jnp.maximum(xy1 - wh1 / 2, xy2 - wh2 / 2)
    hi = jnp.minimum(xy1 + wh1 / 2, xy2 + wh2 / 2)
    inter = jnp.prod(jnp.maximum(hi - lo, 0.0), -1)
    union = wh1[..., 0] * wh1[..., 1] + wh2[..., 0] * wh2[..., 1] - inter
    return inter / jnp.maximum(union, 1e-9)


@register_layer
@dataclass
class Yolo2OutputLayer(Layer):
    """YOLOv2 loss head. No params; input [B,H,W,A*(5+C)]."""
    anchors: Sequence[Sequence[float]] = \
        field(default_factory=lambda: [[1.0, 1.0], [2.0, 2.0]])
    num_classes: int = 1
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5

    # -- Layer interface ---------------------------------------------------
    def init(self, key, input_shape, dtype=jnp.float32):
        a, c = len(self.anchors), self.num_classes
        expect = a * (5 + c)
        if input_shape[-1] != expect:
            raise ValueError(
                f"Yolo2OutputLayer needs {expect} channels "
                f"(A={a} × (5+C={5 + c})), got {input_shape[-1]}")
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return x, state

    def has_params(self):
        return False

    # -- decoding (reference YoloUtils.getPredictedObjects) ---------------
    def activate_predictions(self, x):
        """Raw logits [B,H,W,A*(5+C)] → dict of activated tensors in
        grid units: xy [B,H,W,A,2], wh, conf [B,H,W,A], cls
        [B,H,W,A,C]."""
        b, h, w, _ = x.shape
        a, c = len(self.anchors), self.num_classes
        x = x.reshape(b, h, w, a, 5 + c)
        anchors = jnp.asarray(self.anchors, x.dtype)
        cell_x = jnp.arange(w, dtype=x.dtype)[None, None, :, None]
        cell_y = jnp.arange(h, dtype=x.dtype)[None, :, None, None]
        xy = jax.nn.sigmoid(x[..., 0:2])
        xy = xy.at[..., 0].add(cell_x).at[..., 1].add(cell_y)
        wh = anchors * jnp.exp(x[..., 2:4])
        conf = jax.nn.sigmoid(x[..., 4])
        cls = jax.nn.softmax(x[..., 5:], axis=-1)
        return {"xy": xy, "wh": wh, "conf": conf, "cls": cls}

    # -- loss (reference Yolo2OutputLayer.computeScore) --------------------
    def compute_loss_fn(self):
        anchors = jnp.asarray(self.anchors, jnp.float32)
        a = len(self.anchors)
        lc, ln = self.lambda_coord, self.lambda_no_obj

        def loss(labels, preds, mask=None, weights=None):
            p = self.activate_predictions(preds)
            obj = (labels[..., 2] > 0).astype(preds.dtype)   # [B,H,W]
            # responsible anchor per object cell: max IOU vs priors
            lab_wh = labels[..., 2:4]                        # [B,H,W,2]
            ious = _iou_wh(lab_wh[..., None, :],
                           anchors[None, None, None, :, :])  # [B,H,W,A]
            resp = jax.nn.one_hot(jnp.argmax(ious, -1), a,
                                  dtype=preds.dtype)         # [B,H,W,A]
            resp = resp * obj[..., None]
            n_obj = jnp.maximum(jnp.sum(obj), 1.0)

            # coord loss (responsible anchors only); sqrt-wh as in YOLO
            xy_err = jnp.sum(jnp.square(
                p["xy"] - labels[..., None, 0:2]), -1)
            wh_err = jnp.sum(jnp.square(
                jnp.sqrt(jnp.maximum(p["wh"], 1e-9)) -
                jnp.sqrt(jnp.maximum(labels[..., None, 2:4], 0.0))), -1)
            coord = lc * jnp.sum(resp * (xy_err + wh_err)) / n_obj

            # confidence: responsible → full IOU with truth (position
            # included, the YOLOv2 target); others → 0
            pred_iou = _iou_xywh(p["xy"], p["wh"],
                                 labels[..., None, 0:2],
                                 labels[..., None, 2:4])
            conf_obj = jnp.sum(resp * jnp.square(
                p["conf"] - jax.lax.stop_gradient(pred_iou))) / n_obj
            conf_noobj = ln * jnp.sum(
                (1.0 - resp) * jnp.square(p["conf"])) / \
                jnp.maximum(jnp.sum(1.0 - resp), 1.0)

            # class cross-entropy on object cells
            cls_ce = -jnp.sum(
                resp * jnp.sum(labels[..., None, 4:] *
                               jnp.log(p["cls"] + 1e-9), -1)) / n_obj
            return coord + conf_obj + conf_noobj + cls_ce
        return loss
