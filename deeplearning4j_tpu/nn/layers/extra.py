"""Remaining reference layer families: locally-connected, capsnet
primary/strength, one-class output, shape utilities, 1D/3D pad-crop.

Reference classes (deeplearning4j-nn, org.deeplearning4j.nn.conf.layers):
  LocallyConnected1D / LocallyConnected2D (samediff-backed upstream),
  PrimaryCapsules / CapsuleStrengthLayer (capsnet family, with
  CapsuleLayer in special.py), ``ocnn.OCNNOutputLayer`` (one-class NN,
  Chalapathy et al.), ``misc.FrozenLayerWithBackprop``,
  ``misc.RepeatVector``, ``util.MaskLayer``, Cropping1D / Cropping3D,
  ZeroPadding1DLayer / ZeroPadding3DLayer, Deconvolution3D.

TPU-native design notes: locally-connected layers extract patches once
and run ONE batched einsum over all spatial positions (an MXU batched
matmul) instead of the reference's per-position sliced matmuls; all
shape ops are pure reshapes/pads that XLA fuses away.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn import weights as winit


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


@register_layer
@dataclass
class LocallyConnected2DLayer(Layer):
    """Conv2D with UNSHARED weights per output position (reference
    LocallyConnected2D). One einsum ``bpk,pko->bpo`` over flattened
    positions — a single large batched matmul on the MXU."""
    n_out: int = 0
    kernel: Sequence[int] = (3, 3)
    strides: Sequence[int] = (1, 1)
    padding: str = "VALID"
    has_bias: bool = True

    def _out_hw(self, input_shape):
        h, w, _ = input_shape
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.strides)
        if self.padding.upper() == "SAME":
            return -(-h // sh), -(-w // sw)
        return (h - kh) // sh + 1, (w - kw) // sw + 1

    def init(self, key, input_shape, dtype=jnp.float32):
        c = input_shape[-1]
        kh, kw = _pair(self.kernel)
        oh, ow = self._out_hw(input_shape)
        wi = winit.get(self.weight_init or "xavier")
        params = {"W": wi(key, (oh * ow, kh * kw * c, self.n_out), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((oh * ow, self.n_out), self.bias_init,
                                   dtype)
        return params, {}, (oh, ow, self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.autodiff.ops_registry import OPS
        cols = OPS["im2col"](x, kernel=_pair(self.kernel),
                             strides=_pair(self.strides),
                             padding=self.padding.upper())
        B, oh, ow, K = cols.shape
        z = jnp.einsum("bpk,pko->bpo", cols.reshape(B, oh * ow, K),
                       params["W"])
        if self.has_bias:
            z = z + params["b"]
        y = self._act()(z.reshape(B, oh, ow, self.n_out))
        return self._maybe_dropout(y, train, rng), state


@register_layer
@dataclass
class LocallyConnected1DLayer(Layer):
    """1D unshared-weight convolution (reference LocallyConnected1D).
    Input [B, W, C]."""
    n_out: int = 0
    kernel: int = 3
    stride: int = 1
    padding: str = "VALID"
    has_bias: bool = True

    def _out_w(self, input_shape):
        w, _ = input_shape
        if self.padding.upper() == "SAME":
            return -(-w // self.stride)
        return (w - self.kernel) // self.stride + 1

    def init(self, key, input_shape, dtype=jnp.float32):
        c = input_shape[-1]
        ow = self._out_w(input_shape)
        wi = winit.get(self.weight_init or "xavier")
        params = {"W": wi(key, (ow, self.kernel * c, self.n_out), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((ow, self.n_out), self.bias_init, dtype)
        return params, {}, (ow, self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.autodiff.ops_registry import OPS
        cols = OPS["im2col"](x[:, :, None, :], kernel=(self.kernel, 1),
                             strides=(self.stride, 1),
                             padding=self.padding.upper())
        B, ow = cols.shape[0], cols.shape[1]
        z = jnp.einsum("bpk,pko->bpo",
                       cols.reshape(B, ow, -1), params["W"])
        if self.has_bias:
            z = z + params["b"]
        return self._act()(z), state


@register_layer
@dataclass
class PrimaryCapsules(Layer):
    """Conv → capsule reshape → squash (reference PrimaryCapsules,
    capsnet family; feeds CapsuleLayer)."""
    capsules: Optional[int] = None      # inferred from conv output
    capsule_dim: int = 8
    channels: int = 32                  # conv output = channels*capsule_dim
    kernel: Sequence[int] = (9, 9)
    strides: Sequence[int] = (2, 2)
    padding: str = "VALID"

    def init(self, key, input_shape, dtype=jnp.float32):
        c_in = input_shape[-1]
        kh, kw = _pair(self.kernel)
        n_out = self.channels * self.capsule_dim
        wi = winit.get(self.weight_init or "xavier")
        params = {"W": wi(key, (kh, kw, c_in, n_out), dtype),
                  "b": jnp.full((n_out,), self.bias_init, dtype)}
        h, w, _ = input_shape
        sh, sw = _pair(self.strides)
        if self.padding.upper() == "SAME":
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
        self.capsules = oh * ow * self.channels
        return params, {}, (self.capsules, self.capsule_dim)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        z = lax.conv_general_dilated(
            x, params["W"], window_strides=_pair(self.strides),
            padding=self.padding.upper(),
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + params["b"]
        caps = z.reshape(z.shape[0], -1, self.capsule_dim)
        n2 = jnp.sum(jnp.square(caps), axis=-1, keepdims=True)
        return (n2 / (1 + n2)) * caps / jnp.sqrt(n2 + 1e-9), state


@register_layer
@dataclass
class CapsuleStrengthLayer(Layer):
    """Capsule vector norms → class probabilities (reference
    CapsuleStrengthLayer)."""

    def init(self, key, input_shape, dtype=jnp.float32):
        return {}, {}, (input_shape[0],)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=-1) + 1e-9), state

    def has_params(self):
        return False


@register_layer
@dataclass
class OCNNOutputLayer(Layer):
    """One-class neural network output (reference ocnn.OCNNOutputLayer,
    Chalapathy et al. 2018): decision score w·g(Vx) − r with hinge loss
    (1/ν)·mean(relu(r − w·g(Vx))).

    The margin r lives in ``state`` (non-trainable); the reference
    updates it each epoch to the ν-quantile of scores — call
    :meth:`updated_r` with a batch of scores to do the same. ||V||²+||w||²
    regularization comes from the inherited ``l2`` field."""
    hidden_size: int = 32
    nu: float = 0.04
    initial_r_value: float = 0.1

    def init(self, key, input_shape, dtype=jnp.float32):
        import math
        n_in = int(math.prod(input_shape))
        kv, kw = jax.random.split(key)
        wi = winit.get(self.weight_init or "xavier")
        params = {"V": wi(kv, (n_in, self.hidden_size), dtype),
                  "w": wi(kw, (self.hidden_size, 1), dtype)}
        return params, {"r": jnp.asarray(self.initial_r_value, dtype)}, (1,)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        g = self._act("sigmoid")(x @ params["V"])
        return g @ params["w"] - state["r"], state

    def compute_loss_fn(self):
        nu = self.nu

        def fn(y, out, mask=None):
            # out = score - r; labels unused (one-class)
            h = jax.nn.relu(-out)
            if mask is not None:
                h = h * mask
            return jnp.mean(h) / nu
        return fn

    def updated_r(self, scores) -> jnp.ndarray:
        """New margin: the ν-quantile of decision scores (call between
        epochs, then write into the network state)."""
        return jnp.quantile(scores, self.nu)


@register_layer
@dataclass
class FrozenLayerWithBackprop(Layer):
    """Frozen params but gradients still flow to earlier layers
    (reference misc.FrozenLayerWithBackprop). Functionally: params are
    lax.stop_gradient-ed inside the trace; input gradients pass through."""
    underlying: Optional[Layer] = None

    def init(self, key, input_shape, dtype=jnp.float32):
        return self.underlying.init(key, input_shape, dtype)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        frozen = jax.tree.map(lax.stop_gradient, params)
        return self.underlying.apply(frozen, state, x, train=train,
                                     rng=rng, mask=mask)

    def propagate_mask(self, mask, input_shape):
        return self.underlying.propagate_mask(mask, input_shape)

    @property
    def trainable_(self):
        return False


@register_layer
@dataclass
class MaskLayer(Layer):
    """Zeroes activations at masked timesteps (reference util.MaskLayer).
    Input [B, T, C] with mask [B, T]."""

    def init(self, key, input_shape, dtype=jnp.float32):
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if mask is not None:
            x = x * mask[..., None].astype(x.dtype)
        return x, state

    def has_params(self):
        return False


@register_layer
@dataclass
class RepeatVector(Layer):
    """[B, C] → [B, n, C] (reference misc.RepeatVector)."""
    n: int = 1

    def init(self, key, input_shape, dtype=jnp.float32):
        return {}, {}, (self.n,) + tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.repeat(x[:, None, ...], self.n, axis=1), state

    def has_params(self):
        return False


@register_layer
@dataclass
class SameDiffLayer(Layer):
    """User-defined custom layer — declare parameter shapes and a pure
    forward function; the backward pass comes from autodiff.

    Reference: ``org.deeplearning4j.nn.conf.layers.samediff.SameDiffLayer``
    (defineParameters + defineLayer(sd, input, paramTable)): the
    mechanism for custom layers without hand-written backprop. Here the
    forward is any jax-traceable ``fn(params, x) -> y`` (NDArray/registry
    ops welcome) and ``jax.grad`` through the whole-network step replaces
    the reference's per-layer doDiff graph.

    >>> layer = SameDiffLayer(
    ...     param_shapes={"W": (4, 8), "b": (8,)},
    ...     fn=lambda p, x: jnp.tanh(x @ p["W"] + p["b"]),
    ...     output_shape_fn=lambda s: (8,))
    """
    param_shapes: Optional[dict] = None
    fn: Optional[Callable] = None
    output_shape_fn: Optional[Callable] = None
    mask_fn: Optional[Callable] = None

    def init(self, key, input_shape, dtype=jnp.float32):
        params = {}
        wi = winit.get(self.weight_init or "xavier")
        for name, shape in (self.param_shapes or {}).items():
            key, sub = jax.random.split(key)
            shape = tuple(shape)
            # vectors are biases (constant init); anything with rank ≥ 2
            # needs symmetry breaking regardless of its name
            if len(shape) == 1:
                params[name] = jnp.full(shape, self.bias_init, dtype)
            else:
                params[name] = wi(sub, shape, dtype)
        out = (tuple(self.output_shape_fn(tuple(input_shape)))
               if self.output_shape_fn else tuple(input_shape))
        return params, {}, out

    def _fn_takes_mask(self) -> bool:
        import inspect
        try:
            return "mask" in inspect.signature(self.fn).parameters
        except (TypeError, ValueError):
            return False

    def apply(self, params, state, x, *, train=False, rng=None,
              mask=None):
        if self._fn_takes_mask():
            y = self.fn(params, x, mask=mask)
        else:
            y = self.fn(params, x)
        return self._act()(y), state

    def propagate_mask(self, mask, input_shape):
        if self.mask_fn is not None:
            return self.mask_fn(mask)
        return mask

    def to_dict(self):
        d = super().to_dict()
        d["fn"] = None                  # re-attach after load
        d["output_shape_fn"] = None
        d["mask_fn"] = None
        d["param_shapes"] = {k: list(v)
                             for k, v in (self.param_shapes or
                                          {}).items()}
        return d


@register_layer
@dataclass
class SameDiffOutputLayer(SameDiffLayer):
    """Custom output layer with a user loss (reference
    samediff.SameDiffOutputLayer): ``loss_fn(labels, out) -> scalar``.
    """
    loss_fn: Optional[Callable] = None

    def compute_loss_fn(self):
        import inspect
        lf = self.loss_fn
        takes_mask = "mask" in inspect.signature(lf).parameters

        def fn(y, out, mask=None):
            if takes_mask:
                return lf(y, out, mask=mask)
            if mask is not None:
                # padded timesteps must not contribute to the loss;
                # mask-unaware user losses get a masked-mean fallback
                m = mask
                while m.ndim < out.ndim:
                    m = m[..., None]
                denom = jnp.maximum(jnp.sum(m), 1.0)
                scale = m.size / denom
                return lf(y * m, out * m) * scale
            return lf(y, out)
        return fn

    def to_dict(self):
        d = super().to_dict()
        d["loss_fn"] = None
        return d


@register_layer
@dataclass
class GaussianNoiseLayer(Layer):
    """Train-time additive gaussian noise (reference dropout.GaussianNoise
    as a dropout type; Keras GaussianNoise)."""
    stddev: float = 0.1

    def init(self, key, input_shape, dtype=jnp.float32):
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if train and rng is not None and self.stddev > 0:
            x = x + self.stddev * jax.random.normal(rng, x.shape, x.dtype)
        return x, state

    def has_params(self):
        return False


@register_layer
@dataclass
class GaussianDropoutLayer(Layer):
    """Multiplicative gaussian noise 𝒩(1, rate/(1-rate)) (reference
    dropout.GaussianDropout; Keras GaussianDropout)."""
    rate: float = 0.5

    def init(self, key, input_shape, dtype=jnp.float32):
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if train and rng is not None and 0 < self.rate < 1:
            sd = (self.rate / (1.0 - self.rate)) ** 0.5
            x = x * (1.0 + sd * jax.random.normal(rng, x.shape, x.dtype))
        return x, state

    def has_params(self):
        return False


@register_layer
@dataclass
class Cropping1DLayer(Layer):
    """Crop along the single spatial axis of [B, W, C]
    (reference Cropping1D)."""
    cropping: Sequence[int] = (0, 0)

    def init(self, key, input_shape, dtype=jnp.float32):
        lo, hi = self.cropping
        return {}, {}, (input_shape[0] - lo - hi, input_shape[1])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        lo, hi = self.cropping
        return x[:, lo:x.shape[1] - hi, :], state

    def has_params(self):
        return False


@register_layer
@dataclass
class Cropping3DLayer(Layer):
    """Crop [B, D, H, W, C] (reference Cropping3D)."""
    cropping: Sequence[int] = (0, 0, 0, 0, 0, 0)  # d1,d2,h1,h2,w1,w2

    def init(self, key, input_shape, dtype=jnp.float32):
        d1, d2, h1, h2, w1, w2 = self.cropping
        d, h, w, c = input_shape
        return {}, {}, (d - d1 - d2, h - h1 - h2, w - w1 - w2, c)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        d1, d2, h1, h2, w1, w2 = self.cropping
        _, d, h, w, _ = x.shape
        return x[:, d1:d - d2, h1:h - h2, w1:w - w2, :], state

    def has_params(self):
        return False


@register_layer
@dataclass
class ZeroPadding1DLayer(Layer):
    """Zero-pad the spatial axis of [B, W, C]
    (reference ZeroPadding1DLayer)."""
    padding: Sequence[int] = (1, 1)

    def init(self, key, input_shape, dtype=jnp.float32):
        lo, hi = self.padding
        return {}, {}, (input_shape[0] + lo + hi, input_shape[1])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        lo, hi = self.padding
        return jnp.pad(x, ((0, 0), (lo, hi), (0, 0))), state

    def has_params(self):
        return False


@register_layer
@dataclass
class ZeroPadding3DLayer(Layer):
    """Zero-pad [B, D, H, W, C] (reference ZeroPadding3DLayer)."""
    padding: Sequence[int] = (1, 1, 1, 1, 1, 1)

    def init(self, key, input_shape, dtype=jnp.float32):
        d1, d2, h1, h2, w1, w2 = self.padding
        d, h, w, c = input_shape
        return {}, {}, (d + d1 + d2, h + h1 + h2, w + w1 + w2, c)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        d1, d2, h1, h2, w1, w2 = self.padding
        return jnp.pad(x, ((0, 0), (d1, d2), (h1, h2), (w1, w2),
                           (0, 0))), state

    def has_params(self):
        return False


@register_layer
@dataclass
class Deconvolution3DLayer(Layer):
    """Transposed 3D convolution (reference Deconvolution3D); NDHWC."""
    n_out: int = 0
    kernel: Sequence[int] = (2, 2, 2)
    strides: Sequence[int] = (2, 2, 2)
    padding: str = "SAME"
    has_bias: bool = True

    def init(self, key, input_shape, dtype=jnp.float32):
        d, h, w, c = input_shape
        kd, kh, kw = self.kernel
        sd, sh, sw = self.strides
        wi = winit.get(self.weight_init or "xavier")
        params = {"W": wi(key, (kd, kh, kw, c, self.n_out), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        if self.padding.upper() == "SAME":
            od, oh, ow = d * sd, h * sh, w * sw
        else:
            od = (d - 1) * sd + kd
            oh = (h - 1) * sh + kh
            ow = (w - 1) * sw + kw
        return params, {}, (od, oh, ow, self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        z = lax.conv_transpose(
            x, params["W"], strides=tuple(self.strides),
            padding=self.padding.upper(),
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.has_bias:
            z = z + params["b"]
        return self._act()(z), state
