"""NN framework layer — reference: ``deeplearning4j-nn``.

Config beans (JSON round-trip) build pytree-param models; training is a
single jitted step (grad + optax update), replacing the reference's
Solver/Updater plumbing (SURVEY §3.2) with whole-step XLA compilation.
"""
from deeplearning4j_tpu.nn.config import (
    NeuralNetConfiguration,
    MultiLayerConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration, TransferLearning, TransferLearningHelper)

__all__ = [
    "NeuralNetConfiguration",
    "MultiLayerConfiguration",
    "MultiLayerNetwork",
    "FineTuneConfiguration",
    "TransferLearning",
    "TransferLearningHelper",
]
