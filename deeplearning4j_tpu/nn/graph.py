"""ComputationGraph — DAG models.

Reference: ``org.deeplearning4j.nn.graph.ComputationGraph`` +
``ComputationGraphConfiguration.GraphBuilder`` (SURVEY §2.3):
multi-input/multi-output networks of layers and vertices.

TPU-native: the DAG is walked once at trace time (plain Python in
topological order) — XLA sees a single fused computation; there is no
per-vertex dispatch at runtime. One jitted train step covers all
outputs and losses.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu import dtypes
from deeplearning4j_tpu import obs
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, layer_from_dict
from deeplearning4j_tpu.nn.layers.core import OutputLayer, LossLayer
from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer
from deeplearning4j_tpu.nn.layers.special import FrozenLayer
from deeplearning4j_tpu.nn.multilayer import _FUSABLE
from deeplearning4j_tpu.nn.vertices import (GraphVertex, vertex_from_dict)
from deeplearning4j_tpu.ops import losses as losses_mod
from deeplearning4j_tpu.perf import sentry
from deeplearning4j_tpu.resilience import faults


@dataclass
class _Node:
    name: str
    kind: str                  # "layer" | "vertex"
    obj: Any
    inputs: List[str]


class ComputationGraphConfiguration:
    def __init__(self, inputs: List[str], outputs: List[str],
                 nodes: List[_Node], seed: int = 12345,
                 updater=None, dtype: str = "float32",
                 compute_dtype: Optional[str] = None,
                 input_types: Optional[Dict[str, InputType]] = None,
                 gradient_normalization: Optional[str] = None,
                 gradient_normalization_threshold: float = 1.0):
        self.inputs = inputs
        self.outputs = outputs
        self.nodes = nodes
        self.seed = seed
        self.updater = updater or upd.Sgd(learning_rate=1e-2)
        self.dtype = dtype
        self.compute_dtype = compute_dtype
        self.input_types = input_types or {}
        self.gradient_normalization = gradient_normalization
        self.gradient_normalization_threshold = \
            gradient_normalization_threshold

    def to_json(self) -> str:
        return json.dumps({
            "inputs": self.inputs,
            "outputs": self.outputs,
            "nodes": [{"name": n.name, "kind": n.kind,
                       "inputs": n.inputs, "conf": n.obj.to_dict()}
                      for n in self.nodes],
            "seed": self.seed,
            "updater": self.updater.to_dict(),
            "dtype": self.dtype,
            "compute_dtype": self.compute_dtype,
            "input_types": {k: v.to_dict()
                            for k, v in self.input_types.items()},
            "gradient_normalization": self.gradient_normalization,
            "gradient_normalization_threshold":
                self.gradient_normalization_threshold,
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        d = json.loads(s)
        nodes = []
        for nd in d["nodes"]:
            obj = (layer_from_dict(nd["conf"]) if nd["kind"] == "layer"
                   else vertex_from_dict(nd["conf"]))
            nodes.append(_Node(nd["name"], nd["kind"], obj, nd["inputs"]))
        return ComputationGraphConfiguration(
            inputs=d["inputs"], outputs=d["outputs"], nodes=nodes,
            seed=d.get("seed", 12345),
            updater=upd.updater_from_dict(d["updater"]),
            dtype=d.get("dtype", "float32"),
            compute_dtype=d.get("compute_dtype"),
            input_types={k: InputType.from_dict(v)
                         for k, v in d.get("input_types", {}).items()},
            gradient_normalization=d.get("gradient_normalization"),
            gradient_normalization_threshold=d.get(
                "gradient_normalization_threshold", 1.0))


class GraphBuilder:
    """Reference: ComputationGraphConfiguration.GraphBuilder."""

    def __init__(self, global_conf=None):
        self._g = global_conf
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._nodes: List[_Node] = []
        self._input_types: Dict[str, InputType] = {}

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str
                  ) -> "GraphBuilder":
        if self._g is not None:
            from deeplearning4j_tpu.nn.config import _GLOBAL_DEFAULTS
            for attr in _GLOBAL_DEFAULTS:
                if getattr(layer, attr, None) is None:
                    gv = getattr(self._g, attr, None)
                    if gv is not None:
                        setattr(layer, attr, gv)
        layer.name = name
        self._nodes.append(_Node(name, "layer", layer, list(inputs)))
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str
                   ) -> "GraphBuilder":
        self._nodes.append(_Node(name, "vertex", vertex, list(inputs)))
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs.extend(names)
        return self

    def set_input_types(self, **types: InputType) -> "GraphBuilder":
        self._input_types.update(types)
        return self

    def build(self) -> ComputationGraphConfiguration:
        g = self._g
        return ComputationGraphConfiguration(
            inputs=self._inputs, outputs=self._outputs, nodes=self._nodes,
            seed=g.seed_ if g else 12345,
            updater=g.updater_ if g else None,
            dtype=g.dtype_ if g else "float32",
            compute_dtype=g.compute_dtype_ if g else None,
            input_types=self._input_types,
            gradient_normalization=g.grad_norm_ if g else None,
            gradient_normalization_threshold=(
                g.grad_norm_threshold_ if g else 1.0))


def _group_sig(xs, ys, fms, lms):
    """Grouping key for the scanned device loop: batches scan together
    only when every array shape and the mask structure match."""
    arrs = (list(xs) + list(ys)
            + [m for m in (fms or []) if m is not None]
            + [m for m in (lms or []) if m is not None])
    return (tuple(m is not None for m in (fms or [])),
            tuple(m is not None for m in (lms or [])),
            [np.shape(a) for a in arrs])


def _toposort(nodes: List[_Node], inputs: List[str]) -> List[_Node]:
    done = set(inputs)
    ordered: List[_Node] = []
    pending = list(nodes)
    while pending:
        progressed = False
        for n in list(pending):
            if all(i in done for i in n.inputs):
                ordered.append(n)
                done.add(n.name)
                pending.remove(n)
                progressed = True
        if not progressed:
            missing = {i for n in pending for i in n.inputs} - done
            raise ValueError(f"graph has cycle or missing inputs: "
                             f"{sorted(missing)}")
    return ordered


class ComputationGraph:
    """DAG network (reference ComputationGraph)."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.order = _toposort(conf.nodes, conf.inputs)
        self.params: Dict[str, Any] = {}
        self.state: Dict[str, Any] = {}
        self.opt_state = None
        self.listeners: List[Any] = []
        self.iteration = 0
        self.epoch = 0
        self.score_ = float("nan")
        self._train_step_fn = None
        self._train_loop_fn = None
        self._output_fn = None
        self._optimizer = None
        self._shapes: Dict[str, tuple] = {}
        self._numerics = None        # obs.numerics.NumericsMonitor
        self._diag_step_fn = None
        self.last_numerics = None    # last processed diag record

    # ------------------------------------------------------------------
    def init(self, input_shapes: Optional[Dict[str, tuple]] = None):
        shapes: Dict[str, tuple] = {}
        for name in self.conf.inputs:
            if input_shapes and name in input_shapes:
                shapes[name] = tuple(input_shapes[name])
            elif name in self.conf.input_types:
                shapes[name] = self.conf.input_types[name].shape
            else:
                raise ValueError(f"no input shape for {name!r}")
        dtype = dtypes.resolve(self.conf.dtype)
        key = jax.random.PRNGKey(self.conf.seed)
        for node in self.order:
            in_shapes = [shapes[i] for i in node.inputs]
            if node.kind == "layer":
                key, sub = jax.random.split(key)
                p, s, out = node.obj.init(sub, in_shapes[0], dtype)
                self.params[node.name] = p
                self.state[node.name] = s
            else:
                out = node.obj.output_shape(in_shapes)
            shapes[node.name] = out
        self._shapes = shapes
        self._build_optimizer()
        return self

    def _build_optimizer(self):
        transforms, labels = {}, {}
        for node in self.order:
            if node.kind != "layer":
                continue
            layer = node.obj
            frozen = isinstance(layer, FrozenLayer) or not layer.trainable
            if frozen:
                transforms[node.name] = optax.set_to_zero()
            else:
                chain = [upd.gradient_normalization(
                    self.conf.gradient_normalization,
                    self.conf.gradient_normalization_threshold)]
                if layer.weight_decay:
                    chain.append(optax.add_decayed_weights(
                        layer.weight_decay))
                u = layer.updater or self.conf.updater
                chain.append(u.to_optax())
                transforms[node.name] = optax.chain(*chain)
            labels[node.name] = node.name
        self._optimizer = optax.multi_transform(transforms,
                                                param_labels=labels)
        self.opt_state = self._optimizer.init(self.params)

    # ------------------------------------------------------------------
    def _forward(self, params, state, inputs: Dict[str, jax.Array], *,
                 train: bool, rng, masks=None,
                 pre_output: bool = False, stats_out=None):
        acts: Dict[str, jax.Array] = dict(inputs)
        new_state = {}
        masks = dict(masks or {})
        out_set = set(self.conf.outputs)
        for node in self.order:
            xs = [acts[i] for i in node.inputs]
            m = next((masks.get(i) for i in node.inputs
                      if masks.get(i) is not None), None)
            # device-time attribution (obs/devtime.py): trace-time HLO
            # metadata only — the compiled program is byte-identical
            nscope = obs.devtime.scope(
                f"{node.name}.{type(node.obj).__name__}")
            if node.kind == "vertex":
                with nscope:
                    if node.obj.needs_mask:
                        acts[node.name] = node.obj.apply(xs, mask=m)
                    else:
                        acts[node.name] = node.obj.apply(xs)
                masks[node.name] = node.obj.propagate_mask(m)
                continue
            layer = node.obj
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            if (pre_output and node.name in out_set
                    and isinstance(layer, OutputLayer)):
                with nscope:
                    x = xs[0]
                    if x.ndim > 2 and not hasattr(layer, "loss_rnn"):
                        # flatten to [B, features] like the
                        # MultiLayerNetwork fused path (the old inner
                        # `if x.ndim == 2` made this a dead no-op)
                        x = x.reshape(x.shape[0], -1)
                    z = x @ params[node.name]["W"]
                    if layer.has_bias:
                        z = z + params[node.name]["b"]
                acts[node.name] = z
                new_state[node.name] = state.get(node.name, {})
                masks[node.name] = m
                if stats_out is not None:
                    stats_out[node.name] = obs.numerics.act_summary(z)
                continue
            with nscope:
                y, s = layer.apply(params.get(node.name, {}),
                                   state.get(node.name, {}), xs[0],
                                   train=train, rng=sub, mask=m)
            acts[node.name] = y
            new_state[node.name] = (state.get(node.name, {})
                                    if isinstance(layer,
                                                  BaseRecurrentLayer)
                                    else s)
            if stats_out is not None:
                # diagnostic step: tap this node's output AS TRACED —
                # scalars become aux outputs of the same XLA program
                stats_out[node.name] = obs.numerics.act_summary(y)
            masks[node.name] = layer.propagate_mask(m, None)
        return acts, new_state

    def _out_loss(self, name):
        node = next(n for n in self.order if n.name == name)
        layer = node.obj
        if hasattr(layer, "compute_loss_fn"):
            # layer-defined loss (e.g. Yolo2OutputLayer) — never fused
            return layer.compute_loss_fn(), False
        loss_name = getattr(layer, "loss", None)
        if loss_name is None:
            raise ValueError(f"output {name!r} has no loss")
        act = (layer.activation or "identity").lower()
        fused = (act, loss_name.lower()) in _FUSABLE and \
            isinstance(layer, OutputLayer)
        return loss_name, fused

    def _apply_weight_noise(self, params, rng):
        """Train-time weight noise per layer node (reference
        WeightNoise / DropConnect)."""
        out = dict(params)
        for node in self.order:
            wn = (getattr(node.obj, "weight_noise", None)
                  if node.kind == "layer" else None)
            if wn is not None and node.name in out:
                rng, sub = jax.random.split(rng)
                out[node.name] = wn.apply(out[node.name], sub)
        return out

    def _apply_constraints(self, params):
        """Post-update parameter constraints (reference LayerConstraint)."""
        out = dict(params)
        for node in self.order:
            cs = (getattr(node.obj, "constraints", None)
                  if node.kind == "layer" else None)
            if cs and node.name in out:
                p = out[node.name]
                for c in cs:
                    p = c.apply(p)
                out[node.name] = p
        return out

    def _has_weight_noise(self):
        return any(node.kind == "layer"
                   and getattr(node.obj, "weight_noise", None) is not None
                   for node in self.order)

    def _loss_fn(self, params, state, inputs, labels, masks, lmasks, rng,
                 act_stats=None):
        any_fused = any(self._out_loss(o)[1] for o in self.conf.outputs)
        cd = self.conf.compute_dtype
        if self._has_weight_noise():
            nrng, rng = jax.random.split(rng)
            params = self._apply_weight_noise(params, nrng)
        if cd is not None:
            # bf16 fwd/bwd, fp32 master params (grads return fp32)
            params = dtypes.cast_float_tree(params, cd)
            inputs = dtypes.cast_float_tree(inputs, cd)
        acts, new_state = self._forward(params, state, inputs, train=True,
                                        rng=rng, masks=masks,
                                        pre_output=any_fused,
                                        stats_out=act_stats)
        total = 0.0
        for name, y in zip(self.conf.outputs, labels):
            loss_name, fused = self._out_loss(name)
            fn = losses_mod.get(loss_name)
            kw = {"from_logits": True} if fused else {}
            lm = lmasks.get(name) if lmasks else None
            logits = acts[name]
            # devtime scope: names each output's loss device share
            with obs.devtime.scope(f"loss.{loss_name}"):
                if cd is not None and losses_mod.wants_f32_logits(
                        fn, fused):
                    logits = logits.astype(jnp.float32)
                total = total + fn(y, logits, mask=lm, **kw)
        return total, new_state

    # ------------------------------------------------------------------
    def _update(self, params, opt_state, state, inputs, labels, masks,
                lmasks, rng):
        """One gradient+optimizer update — the single source of truth
        traced by both the per-batch step and the scanned loop."""
        (loss, new_state), grads = jax.value_and_grad(
            self._loss_fn, has_aux=True)(params, state, inputs,
                                         labels, masks, lmasks, rng)
        # devtime scope: names the optimizer's device share next to
        # the per-node forward/backward scopes
        with obs.devtime.scope("optimizer.update"):
            updates, opt_state = self._optimizer.update(grads,
                                                        opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            params = self._apply_constraints(params)
        return params, opt_state, new_state, loss

    def _make_train_step(self):
        return sentry.jit(self._update,
                          name="ComputationGraph.train_step",
                          donate_argnums=(0, 1, 2))

    # ------------------------------------------------------------------
    # numerics observatory (obs/numerics.py — ARCHITECTURE.md §11)
    # ------------------------------------------------------------------
    def _layer_names(self):
        """Parametrized nodes in topological order — the attribution
        ordering the NaN sentinel scans."""
        return [n.name for n in self.order if n.kind == "layer"]

    def monitor_numerics(self, every: int = 1,
                         histograms: bool = False,
                         raise_on_nonfinite: bool = True):
        """Attach the numerics observatory (see
        ``MultiLayerNetwork.monitor_numerics``)."""
        self._numerics = obs.numerics.NumericsMonitor(
            every=every, histograms=histograms,
            raise_on_nonfinite=raise_on_nonfinite)
        self._diag_step_fn = None   # config is traced into the program
        return self

    def _make_diag_step(self):
        histograms = self._numerics.histograms \
            if self._numerics is not None else False
        layers = self._layer_names()

        def diag_update(params, opt_state, state, inputs, labels,
                        masks, lmasks, rng):
            def lf(p):
                stats = {}
                loss, new_state = self._loss_fn(
                    p, state, inputs, labels, masks, lmasks, rng,
                    act_stats=stats)
                return loss, (new_state, stats)

            (loss, (new_state, act_stats)), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            updates, new_opt = self._optimizer.update(grads, opt_state,
                                                      params)
            new_params = optax.apply_updates(params, updates)
            new_params = self._apply_constraints(new_params)
            diag = obs.numerics.build_diag(
                new_params, grads, updates, act_stats, layers,
                histograms=histograms)
            # packed: 2 host transfers per diag step instead of ~10
            return (new_params, new_opt, new_state, loss,
                    obs.numerics.pack_diag(diag))

        return sentry.jit(diag_update,
                          name="ComputationGraph.diag_step",
                          donate_argnums=(0, 1, 2))

    def _fit_batch_diag(self, inputs, labels, masks, lmasks, t0):
        """Cadence-gated diagnostic step (see
        ``MultiLayerNetwork._fit_batch_diag``)."""
        if self._diag_step_fn is None:
            self._diag_step_fn = self._make_diag_step()
        rng = jax.random.fold_in(jax.random.PRNGKey(self.conf.seed),
                                 self.iteration)
        t1 = obs.now()
        try:
            self.params, self.opt_state, self.state, loss, diag = \
                self._diag_step_fn(self.params, self.opt_state,
                                   self.state, inputs, labels, masks,
                                   lmasks, rng)
            t2 = obs.now()
            self.score_ = float(loss)   # blocking device sync
        except Exception as e:       # HBM OOM → diagnostic dump
            from deeplearning4j_tpu.utils import crashreport
            if crashreport.is_oom(e):
                path = crashreport.write_memory_crash_dump(self, e)
                if path:
                    raise RuntimeError(
                        f"diagnostic training step ran out of device "
                        f"memory (the numerics aux outputs keep "
                        f"grads+updates alive together — try a "
                        f"sparser cadence); crash dump written to "
                        f"{path}") from e
            raise
        obs.record_step("ComputationGraph.fit", t0, t1, t2, obs.now())
        self.iteration += 1
        self._numerics.process(self, diag, self._layer_names(),
                               entry="ComputationGraph")
        tl0 = obs.now()
        for l in self.listeners:
            l.iteration_done(self, self.iteration, self.epoch)
        if self.listeners and obs.trace.enabled():
            obs.trace.add_span("ComputationGraph.fit/listeners",
                               tl0, obs.now())

    def _make_train_loop(self):
        """K train steps per dispatched executable (``lax.scan`` over
        stacked batches) — the idiomatic TPU device loop. Each launch
        through the runtime costs ~10ms of host/dispatch latency that a
        per-batch ``fit`` pays per step; the scanned loop pays it once
        per K steps. Numerically identical to K sequential steps: the
        per-iteration rng keys are precomputed and scanned over.
        Masked batches scan too — the mask stacks are (possibly empty)
        dicts, so each mask structure gets its own trace."""
        def one(carry, batch):
            params, opt_state, state = carry
            inputs, labels, masks, lmasks, rng = batch
            params, opt_state, new_state, loss = self._update(
                params, opt_state, state, inputs, labels, masks,
                lmasks, rng)
            return (params, opt_state, new_state), loss

        def loop(params, opt_state, state, inputs_stack, labels_stack,
                 masks_stack, lmasks_stack, rng_stack):
            (p, o, s), losses = jax.lax.scan(
                one, (params, opt_state, state),
                (inputs_stack, labels_stack, masks_stack, lmasks_stack,
                 rng_stack))
            return p, o, s, losses

        return sentry.jit(loop, name="ComputationGraph.train_loop",
                          donate_argnums=(0, 1, 2))

    def _refresh_ambient_trace(self):
        """Drop jitted caches when the ambient distributed context has
        changed since tracing (see MultiLayerNetwork's counterpart)."""
        if not any(node.kind == "layer"
                   and getattr(node.obj, "sequence_parallel", None)
                   for node in self.order):
            return
        from deeplearning4j_tpu.parallel.mesh import context_epoch
        e = context_epoch()
        if getattr(self, "_ctx_epoch", None) != e:
            self._ctx_epoch = e
            self._train_step_fn = None
            self._train_loop_fn = None
            self._output_fn = None
            self._diag_step_fn = None

    def _fit_group(self, group):
        """Run a group of uniformly-shaped batches (same mask
        structure) in one scanned call (see ``_make_train_loop``)."""
        nm = self._numerics
        if nm is not None and any(nm.due(self.iteration + i)
                                  for i in range(len(group))):
            # a diagnostic step is due inside this group: the scanned
            # loop has no per-step aux outputs, so run the group's
            # batches individually (the cadence path, not the hot one)
            nm.note_group_split(len(group))
            for item in group:
                self._fit_batch(*item)
            return
        t0 = obs.now()
        faults.inject("step")       # site: step dispatch (resilience/)
        self._refresh_ambient_trace()
        if self._train_loop_fn is None:
            self._train_loop_fn = self._make_train_loop()
        inputs = {n: jnp.stack([jnp.asarray(np.asarray(item[0][i]))
                                for item in group])
                  for i, n in enumerate(self.conf.inputs)}
        labels = [jnp.stack([jnp.asarray(np.asarray(item[1][j]))
                             for item in group])
                  for j in range(len(group[0][1]))]
        fms0, lms0 = group[0][2], group[0][3]
        masks = {n: jnp.stack([jnp.asarray(np.asarray(item[2][i]))
                               for item in group])
                 for i, n in enumerate(self.conf.inputs)
                 if fms0 and i < len(fms0) and fms0[i] is not None}
        lmasks = {n: jnp.stack([jnp.asarray(np.asarray(item[3][j]))
                                for item in group])
                  for j, n in enumerate(self.conf.outputs)
                  if lms0 and j < len(lms0) and lms0[j] is not None}
        base = jax.random.PRNGKey(self.conf.seed)
        rngs = jnp.stack([jax.random.fold_in(base, self.iteration + i)
                          for i in range(len(group))])
        t1 = obs.now()
        try:
            self.params, self.opt_state, self.state, losses = \
                self._train_loop_fn(self.params, self.opt_state,
                                    self.state, inputs, labels, masks,
                                    lmasks, rngs)
        except Exception as e:       # HBM OOM → diagnostic dump
            from deeplearning4j_tpu.utils import crashreport
            if crashreport.is_oom(e):
                path = crashreport.write_memory_crash_dump(self, e)
                if path:
                    raise RuntimeError(
                        f"scanned train loop ran out of device memory "
                        f"(steps_per_loop={len(group)} stacks the group "
                        f"on device — try a smaller value); crash dump "
                        f"written to {path}") from e
            raise
        t2 = obs.now()
        losses = np.asarray(losses)   # one host transfer for the group
        t3 = obs.now()
        obs.record_step("ComputationGraph.fit", t0, t1, t2, t3,
                        args={"steps": len(group)})
        tl0 = obs.now()
        for loss in losses:
            self.score_ = float(loss)
            self.iteration += 1
            for l in self.listeners:
                l.iteration_done(self, self.iteration, self.epoch)
        if nm is not None:
            nm.note_score(self.score_)
        if self.listeners and obs.trace.enabled():
            obs.trace.add_span("ComputationGraph.fit/listeners",
                               tl0, obs.now())

    def fit(self, features, labels=None, *, epochs: int = 1,
            features_masks=None, labels_masks=None,
            steps_per_loop: int = 1):
        """fit(MultiDataSet iterator) | fit([x...], [y...]) | fit(x, y).

        ``features_masks``: sequence aligned with inputs ([B,T] each or
        None); ``labels_masks``: aligned with outputs — reference
        MultiDataSet mask semantics (per-position loss masking, e.g.
        MLM masked positions)."""
        if labels is not None:
            xs = features if isinstance(features, (list, tuple)) \
                else [features]
            ys = labels if isinstance(labels, (list, tuple)) else [labels]
            self._fit_batch(xs, ys, features_masks, labels_masks)
            return self
        it = features
        for _ in range(epochs):
            for l in self.listeners:
                l.on_epoch_start(self)
            if hasattr(it, "reset"):
                it.reset()
            group: list = []
            prev_sig = None
            src = iter(it)
            while True:
                te0 = obs.now()     # iterator wait = ETL attribution
                try:
                    mds = next(src)
                except StopIteration:
                    break
                obs.record_etl("ComputationGraph.fit", te0, obs.now())
                if hasattr(mds, "features"):
                    xs = (mds.features
                          if isinstance(mds.features, list)
                          else [mds.features])
                    ys = (mds.labels if isinstance(mds.labels, list)
                          else [mds.labels])
                    fms = getattr(mds, "features_masks", None)
                    lms = getattr(mds, "labels_masks", None)
                else:
                    xs, ys = mds
                    xs = xs if isinstance(xs, list) else [xs]
                    ys = ys if isinstance(ys, list) else [ys]
                    fms = lms = None
                if steps_per_loop > 1:
                    # group uniformly-shaped batches (masks included —
                    # masked BERT batches keep the device loop) into
                    # one scanned call; a shape or mask-structure
                    # change flushes the group
                    sig = _group_sig(xs, ys, fms, lms)
                    if group and sig != prev_sig:
                        self._flush_group(group)
                    group.append((xs, ys, fms, lms))
                    prev_sig = sig
                    if len(group) == steps_per_loop:
                        self._flush_group(group)
                else:
                    self._flush_group(group)
                    self._fit_batch(xs, ys, fms, lms)
            self._flush_group(group)
            for l in self.listeners:
                l.on_epoch_end(self)
            self.epoch += 1
        return self

    def _flush_group(self, group):
        if not group:
            return
        if len(group) == 1:
            self._fit_batch(*group[0])
        else:
            self._fit_group(list(group))
        group.clear()

    def _fit_batch(self, xs, ys, fms=None, lms=None):
        t0 = obs.now()
        faults.inject("step")       # site: step dispatch (resilience/)
        self._refresh_ambient_trace()
        if self._train_step_fn is None:
            self._train_step_fn = self._make_train_step()
        inputs = {n: jnp.asarray(np.asarray(x))
                  for n, x in zip(self.conf.inputs, xs)}
        labels = [jnp.asarray(np.asarray(y)) for y in ys]
        masks = {n: jnp.asarray(np.asarray(m))
                 for n, m in zip(self.conf.inputs, fms or [])
                 if m is not None}
        lmasks = {n: jnp.asarray(np.asarray(m))
                  for n, m in zip(self.conf.outputs, lms or [])
                  if m is not None}
        nm = self._numerics     # off path: one attribute check
        if nm is not None and nm.due(self.iteration):
            return self._fit_batch_diag(inputs, labels, masks, lmasks,
                                        t0)
        # devtime + commtime capture windows (obs/devtime.py,
        # obs/commtime.py): off path is one module-global branch
        # inside each hook
        obs.devtime.step_started(self.iteration)
        obs.commtime.step_started(self.iteration)
        rng = jax.random.fold_in(jax.random.PRNGKey(self.conf.seed),
                                 self.iteration)
        t1 = obs.now()
        self.params, self.opt_state, self.state, loss = \
            self._train_step_fn(self.params, self.opt_state, self.state,
                                inputs, labels, masks, lmasks, rng)
        t2 = obs.now()
        self.score_ = float(loss)     # blocking device sync
        obs.devtime.step_ended(self._train_step_fn)
        obs.commtime.step_ended(self._train_step_fn)
        obs.record_step("ComputationGraph.fit", t0, t1, t2, obs.now())
        self.iteration += 1
        if nm is not None:
            nm.note_score(self.score_)
        tl0 = obs.now()
        for l in self.listeners:
            l.iteration_done(self, self.iteration, self.epoch)
        if self.listeners and obs.trace.enabled():
            obs.trace.add_span("ComputationGraph.fit/listeners",
                               tl0, obs.now())

    # ------------------------------------------------------------------
    def _make_output_fn(self):
        cd = self.conf.compute_dtype

        def infer(params, state, inputs):
            if cd is not None:
                params = dtypes.cast_float_tree(params, cd)
                state = dtypes.cast_float_tree(state, cd)
                inputs = dtypes.cast_float_tree(inputs, cd)
            acts, _ = self._forward(params, state, inputs,
                                    train=False, rng=None)
            outs = [acts[o] for o in self.conf.outputs]
            if cd is not None:
                outs = [o.astype(jnp.float32) for o in outs]
            return outs

        return sentry.jit(infer, name="ComputationGraph.output")

    def output(self, *features, train: bool = False):
        """Returns a list of output activations (reference
        ComputationGraph.output)."""
        self._refresh_ambient_trace()
        if self._output_fn is None:
            self._output_fn = self._make_output_fn()
        inputs = {n: jnp.asarray(np.asarray(x))
                  for n, x in zip(self.conf.inputs, features)}
        return self._output_fn(self.params, self.state, inputs)

    def warmup(self, specs):
        """AOT-compile the train step, scanned loop, and output fn for
        every declared shape bucket (see ``perf.warmup``)."""
        from deeplearning4j_tpu.perf.warmup import warmup_network
        self._refresh_ambient_trace()
        return warmup_network(self, specs)

    def output_single(self, *features):
        return self.output(*features)[0]

    def score(self, dataset=None) -> float:
        return self.score_

    def evaluate(self, iterator):
        from deeplearning4j_tpu.eval_.evaluation import Evaluation
        e = Evaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            x, y = (ds.features, ds.labels) if hasattr(ds, "features") \
                else ds
            out = self.output(x)[0]
            e.eval(np.asarray(y), np.asarray(out))
        return e

    def num_params(self) -> int:
        return sum(int(np.prod(np.shape(l)))
                   for l in jax.tree.leaves(self.params))

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def summary(self) -> str:
        lines = ["=" * 76,
                 f"{'Node':<24}{'Type':<26}{'Output':<16}{'Params':>8}",
                 "=" * 76]
        total = 0
        for node in self.order:
            n = 0
            if node.kind == "layer":
                n = sum(int(np.prod(np.shape(l))) for l in
                        jax.tree.leaves(self.params[node.name]))
            total += n
            lines.append(
                f"{node.name:<24}{type(node.obj).__name__:<26}"
                f"{str(self._shapes.get(node.name)):<16}{n:>8,}")
        lines.append("=" * 76)
        lines.append(f"Total params: {total:,}")
        return "\n".join(lines)
