"""Graph vertices — reference: ``org.deeplearning4j.nn.conf.graph.*`` /
``org.deeplearning4j.nn.graph.vertex.impl.*`` (MergeVertex,
ElementWiseVertex, SubsetVertex, StackVertex, UnstackVertex, ScaleVertex,
ShiftVertex, L2NormalizeVertex, ReshapeVertex, AttentionVertex).

A vertex is a paramless (or small-param) multi-input op in a
ComputationGraph; one dataclass per vertex with ``apply(inputs)``.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

_VERTEX_REGISTRY: Dict[str, type] = {}


def register_vertex(cls):
    _VERTEX_REGISTRY[cls.__name__] = cls
    return cls


def vertex_from_dict(d: Dict[str, Any]):
    d = dict(d)
    cls = _VERTEX_REGISTRY[d.pop("@class")]
    if isinstance(d.get("preprocessor"), dict):
        from deeplearning4j_tpu.nn.preprocessors import (
            preprocessor_from_dict)
        d["preprocessor"] = preprocessor_from_dict(d["preprocessor"])
    return cls(**{k: v for k, v in d.items()
                  if k in {f.name for f in dataclasses.fields(cls)}})


@dataclass
class GraphVertex:
    #: subclasses that consume the sequence mask set this True; the
    #: graph then calls ``apply(inputs, mask=m)``
    needs_mask = False

    def apply(self, inputs: List[jax.Array]) -> jax.Array:
        raise NotImplementedError

    def output_shape(self, input_shapes: List[tuple]) -> tuple:
        raise NotImplementedError

    def to_dict(self):
        out = {"@class": type(self).__name__}
        for f in dataclasses.fields(self):
            out[f.name] = getattr(self, f.name)
        return out

    def propagate_mask(self, mask):
        """Transform the incoming [B,T] mask for downstream nodes
        (mirrors Layer.propagate_mask). Default: unchanged."""
        return mask


@register_vertex
@dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature axis (reference MergeVertex)."""
    axis: int = -1

    def apply(self, inputs):
        return jnp.concatenate(inputs, axis=self.axis)

    def output_shape(self, shapes):
        # shapes are batchless; ``apply`` sees batched arrays, so
        # normalize the axis against the batched rank and shift down by
        # one (batched axis 0 = batch, unmergeable)
        out = list(shapes[0])
        batched_rank = len(out) + 1
        ax = self.axis if self.axis >= 0 else self.axis + batched_rank
        if ax == 0:
            raise ValueError("MergeVertex cannot concatenate along "
                             "the batch axis")
        ax -= 1
        out[ax] = sum(s[ax] for s in shapes)
        return tuple(out)


@register_vertex
@dataclass
class ElementWiseVertex(GraphVertex):
    """Elementwise add/sub/mul/avg/max (reference ElementWiseVertex.Op)."""
    op: str = "add"

    def apply(self, inputs):
        op = self.op.lower()
        out = inputs[0]
        if op == "add":
            for x in inputs[1:]:
                out = out + x
        elif op in ("sub", "subtract"):
            for x in inputs[1:]:
                out = out - x
        elif op in ("mul", "product"):
            for x in inputs[1:]:
                out = out * x
        elif op in ("avg", "average"):
            out = sum(inputs) / len(inputs)
        elif op == "max":
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
        else:
            raise ValueError(f"unknown elementwise op {self.op!r}")
        return out

    def output_shape(self, shapes):
        return tuple(shapes[0])


@register_vertex
@dataclass
class SubsetVertex(GraphVertex):
    """Feature-range slice [from, to] inclusive (reference SubsetVertex)."""
    from_: int = 0
    to: int = 0

    def apply(self, inputs):
        return inputs[0][..., self.from_:self.to + 1]

    def output_shape(self, shapes):
        s = list(shapes[0])
        s[-1] = self.to - self.from_ + 1
        return tuple(s)


@register_vertex
@dataclass
class StackVertex(GraphVertex):
    """Stack along batch axis (reference StackVertex)."""

    def apply(self, inputs):
        return jnp.concatenate(inputs, axis=0)

    def output_shape(self, shapes):
        return tuple(shapes[0])


@register_vertex
@dataclass
class UnstackVertex(GraphVertex):
    """Take slice ``index`` of ``num`` along batch (reference
    UnstackVertex)."""
    index: int = 0
    num: int = 2

    def apply(self, inputs):
        x = inputs[0]
        n = x.shape[0] // self.num
        return x[self.index * n:(self.index + 1) * n]

    def output_shape(self, shapes):
        return tuple(shapes[0])


@register_vertex
@dataclass
class ScaleVertex(GraphVertex):
    scale: float = 1.0

    def apply(self, inputs):
        return inputs[0] * self.scale

    def output_shape(self, shapes):
        return tuple(shapes[0])


@register_vertex
@dataclass
class ShiftVertex(GraphVertex):
    shift: float = 0.0

    def apply(self, inputs):
        return inputs[0] + self.shift

    def output_shape(self, shapes):
        return tuple(shapes[0])


@register_vertex
@dataclass
class L2NormalizeVertex(GraphVertex):
    eps: float = 1e-8

    def apply(self, inputs):
        x = inputs[0]
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
        return x / jnp.maximum(n, self.eps)

    def output_shape(self, shapes):
        return tuple(shapes[0])


@register_vertex
@dataclass
class ReshapeVertex(GraphVertex):
    """Reshape trailing dims, batch preserved (reference ReshapeVertex)."""
    shape: Sequence[int] = ()

    def apply(self, inputs):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.shape))

    def output_shape(self, shapes):
        return tuple(self.shape)


@register_vertex
@dataclass
class FlattenVertex(GraphVertex):
    """Collapse all trailing dims to one feature axis (Keras-import shim
    for Flatten feeding non-Dense consumers; reference PreprocessorVertex
    + CnnToFeedForwardPreProcessor)."""

    def apply(self, inputs):
        x = inputs[0]
        return x.reshape(x.shape[0], -1)

    def output_shape(self, shapes):
        n = 1
        for d in shapes[0]:
            if d is None or int(d) < 0:
                raise ValueError(
                    "FlattenVertex needs fully-known input dims; got "
                    f"{shapes[0]} (dynamic time axes cannot be flattened)")
            n *= int(d)
        return (n,)

    def propagate_mask(self, mask):
        return None          # time axis is gone


@register_vertex
@dataclass
class PoolHelperVertex(GraphVertex):
    """Strips first row/col (reference PoolHelperVertex, googlenet shim)."""

    def apply(self, inputs):
        return inputs[0][:, 1:, 1:, :]

    def output_shape(self, shapes):
        s = shapes[0]
        return (s[0] - 1, s[1] - 1, s[2])


@register_vertex
@dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two activation tensors → [B, 1]
    (reference L2Vertex, used by siamese/triplet setups)."""
    eps: float = 1e-8

    def apply(self, inputs):
        a = inputs[0].reshape(inputs[0].shape[0], -1)
        b = inputs[1].reshape(inputs[1].shape[0], -1)
        d2 = jnp.sum(jnp.square(a - b), axis=-1, keepdims=True)
        # guarded sqrt: finite grad when the two branches coincide
        safe = jnp.where(d2 > 0, d2, 1.0)
        return jnp.where(d2 > 0, jnp.sqrt(safe), self.eps)

    def output_shape(self, shapes):
        return (1,)

    def propagate_mask(self, mask):
        return None


@register_vertex
@dataclass
class LastTimeStepVertex(GraphVertex):
    """Select the last non-masked timestep of [B, T, F] → [B, F]
    (reference LastTimeStepVertex)."""
    needs_mask = True

    def apply(self, inputs, mask=None):
        x = inputs[0]
        if mask is None:
            return x[:, -1, :]
        lengths = jnp.sum(mask.astype(jnp.int32), axis=1)
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(
            x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0, :]

    def output_shape(self, shapes):
        return (shapes[0][-1],)

    def propagate_mask(self, mask):
        return None          # time axis is gone


@register_vertex
@dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """Broadcast a [B, F] vector across the time axis of a reference
    sequence input → [B, T, F] (reference DuplicateToTimeSeriesVertex).
    inputs = [vector, timeseries-shape-reference]."""

    def apply(self, inputs):
        vec, ts = inputs[0], inputs[1]
        return jnp.broadcast_to(vec[:, None, :],
                                (vec.shape[0], ts.shape[1],
                                 vec.shape[-1]))

    def output_shape(self, shapes):
        return (shapes[1][0], shapes[0][-1])


@register_vertex
@dataclass
class ReverseTimeSeriesVertex(GraphVertex):
    """Mask-aware time reversal of [B, T, F] (reference
    ReverseTimeSeriesVertex): only the valid prefix is reversed, padding
    stays in place."""
    needs_mask = True

    def apply(self, inputs, mask=None):
        x = inputs[0]
        if mask is None:
            return jnp.flip(x, axis=1)
        lengths = jnp.sum(mask.astype(jnp.int32), axis=1)
        t = jnp.arange(x.shape[1])
        idx = jnp.where(t[None, :] < lengths[:, None],
                        lengths[:, None] - 1 - t[None, :], t[None, :])
        return jnp.take_along_axis(x, idx[:, :, None], axis=1)

    def output_shape(self, shapes):
        return tuple(shapes[0])


@register_vertex
@dataclass
class PreprocessorVertex(GraphVertex):
    """Wraps an InputPreProcessor as a vertex (reference
    PreprocessorVertex)."""
    preprocessor: Optional[Any] = None

    def apply(self, inputs):
        return self.preprocessor.pre_process(inputs[0])

    def output_shape(self, shapes):
        return self.preprocessor.output_shape(shapes[0])

    def propagate_mask(self, mask):
        return self.preprocessor.propagate_mask(mask)

    def to_dict(self):
        return {"@class": type(self).__name__,
                "preprocessor": self.preprocessor.to_dict()}


@register_vertex
@dataclass
class AttentionVertex(GraphVertex):
    """Cross-attention vertex (reference AttentionVertex over
    multi_head_dot_product_attention): inputs [queries, keys, values]
    (or [q, kv]). Paramless scaled dot-product here; for projected
    attention use nn.layers.attention.MultiHeadAttention."""
    n_heads: int = 1

    def apply(self, inputs):
        from deeplearning4j_tpu.nn.layers.attention import (
            scaled_dot_attention)
        q = inputs[0]
        k = inputs[1]
        v = inputs[2] if len(inputs) > 2 else inputs[1]

        def split(x):
            b, t, f = x.shape
            return x.reshape(b, t, self.n_heads, f // self.n_heads)

        out = scaled_dot_attention(split(q), split(k), split(v))
        b, t, h, d = out.shape
        return out.reshape(b, t, h * d)

    def output_shape(self, shapes):
        return tuple(shapes[0])
