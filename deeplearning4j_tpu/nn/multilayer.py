"""MultiLayerNetwork — reference:
``org.deeplearning4j.nn.multilayer.MultiLayerNetwork`` (~4k-line class,
SURVEY §2.3/§3.2).

TPU-native redesign: instead of the reference's per-op eager dispatch
(layer.activate → JNI → kernel, one crossing per op), the WHOLE training
step — forward, loss, backward, updater, param update — is one traced
``jax.jit`` computation: XLA fuses it and keeps everything in HBM.
``fit`` then just streams batches into the compiled step.

Supports: fit/output/score, masks, truncated BPTT with stored recurrent
state (reference rnnTimeStep / rnnActivateUsingStoredState), listeners,
per-layer updater/LR overrides, frozen layers, l1/l2/weight-decay,
gradient normalization modes.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu import dtypes
from deeplearning4j_tpu import obs
from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.layers.core import OutputLayer, LossLayer
from deeplearning4j_tpu.nn.layers.recurrent import (
    BaseRecurrentLayer, RnnOutputLayer, RnnLossLayer)
from deeplearning4j_tpu.nn.layers.special import FrozenLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.ops import losses as losses_mod
from deeplearning4j_tpu.perf import sentry
from deeplearning4j_tpu.resilience import faults

# losses that support the fused from_logits path, keyed by activation
_FUSABLE = {
    ("softmax", "mcxent"), ("softmax", "negativeloglikelihood"),
    ("softmax", "sparse_mcxent"), ("sigmoid", "xent"),
    ("sigmoid", "binary_xent"),
}


def _lname(i: int) -> str:
    return f"layer_{i}"


class MultiLayerNetwork:
    """Sequential stack model (reference MultiLayerNetwork)."""

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers: List[Layer] = conf.layers
        self.params: Dict[str, Any] = {}
        self.state: Dict[str, Any] = {}
        self.opt_state = None
        self.listeners: List[Any] = []
        self.iteration = 0
        self.epoch = 0
        self._rnn_state: Optional[Dict[str, Any]] = None  # stored-state API
        self._train_step_fn = None
        self._train_loop_fn = None
        self._output_fn = None
        self._optimizer = None
        self.score_ = float("nan")
        self._numerics = None        # obs.numerics.NumericsMonitor
        self._diag_step_fn = None
        self.last_numerics = None    # last processed diag record

    # ------------------------------------------------------------------
    # init
    # ------------------------------------------------------------------
    def init(self, input_shape: Optional[Tuple[int, ...]] = None):
        """Build params (reference MultiLayerNetwork.init()). Shape comes
        from conf.input_type unless given explicitly (no batch dim)."""
        if input_shape is None:
            if self.conf.input_type is None:
                raise ValueError("init() needs input_shape or "
                                 "conf.input_type")
            input_shape = self.conf.input_type.shape
            if self.conf.input_type.kind == "rnn" and input_shape[0] == -1:
                input_shape = (None,) + input_shape[1:]
        dtype = dtypes.resolve(self.conf.dtype)
        key = jax.random.PRNGKey(self.conf.seed)
        shape = tuple(input_shape)
        self._input_shape = shape
        self._layer_shapes = []
        for i, layer in enumerate(self.layers):
            proc = self.conf.input_preprocessors.get(i)
            if proc is not None:
                shape = proc.output_shape(shape)
            key, sub = jax.random.split(key)
            p, s, shape = layer.init(sub, shape, dtype)
            self.params[_lname(i)] = p
            self.state[_lname(i)] = s
            self._layer_shapes.append(shape)
        self._output_shape = shape
        # tied params are NOT master parameters: drop them after init
        # (shape-checked against their source); _forward rebuilds them
        for di, dn, si, sn, tr in self.conf.tied_weights:
            src = self.params[_lname(si)][sn]
            dst = self.params[_lname(di)].pop(dn)
            want = src.shape[::-1] if tr else src.shape
            if tuple(dst.shape) != tuple(want):
                raise ValueError(
                    f"tie_weights: layer_{di}.{dn} {dst.shape} != "
                    f"layer_{si}.{sn}{'(transposed)' if tr else ''} "
                    f"{want}")
        self._build_optimizer()
        return self

    def _materialize_ties(self, params):
        """Rebuild tied params from their source inside the traced
        forward — gradients accumulate onto the source from both
        uses."""
        ties = getattr(self.conf, "tied_weights", None)
        if not ties:
            return params
        out = dict(params)
        for di, dn, si, sn, tr in ties:
            src = out[_lname(si)][sn]
            blk = dict(out.get(_lname(di), {}))
            blk[dn] = src.T if tr else src
            out[_lname(di)] = blk
        return out

    def _layer_updater(self, layer: Layer):
        u = layer.updater
        if u is None and layer.learning_rate is not None:
            import copy
            u = copy.deepcopy(self.conf.updater)
            u.learning_rate = layer.learning_rate
            u.schedule = None
        return u or self.conf.updater

    def _build_optimizer(self):
        transforms, labels = {}, {}
        for i, layer in enumerate(self.layers):
            name = _lname(i)
            frozen = isinstance(layer, FrozenLayer) or not layer.trainable
            if frozen:
                transforms[name] = optax.set_to_zero()
            else:
                chain = [upd.gradient_normalization(
                    self.conf.gradient_normalization,
                    self.conf.gradient_normalization_threshold)]
                if layer.weight_decay:
                    chain.append(optax.add_decayed_weights(
                        layer.weight_decay))
                chain.append(self._layer_updater(layer).to_optax())
                transforms[name] = optax.chain(*chain)
            labels[name] = name
        self._optimizer = optax.multi_transform(
            transforms, param_labels=labels)
        self.opt_state = self._optimizer.init(self.params)

    # ------------------------------------------------------------------
    # forward
    # ------------------------------------------------------------------
    def _forward(self, params, state, x, *, train, rng, mask=None,
                 rnn_init=None, stop_at: Optional[int] = None,
                 pre_output_last: bool = False, stats_out=None):
        """Returns (activation, new_state, rnn_states)."""
        if not params:
            raise RuntimeError(
                "Network has no parameters — call init() before "
                "fit()/output() (reference: MultiLayerNetwork.init()).")
        params = self._materialize_ties(params)
        new_state = {}
        rnn_states = {}
        n = len(self.layers) if stop_at is None else stop_at
        preprocs = self.conf.input_preprocessors
        for i in range(n):
            layer = self.layers[i]
            name = _lname(i)
            proc = preprocs.get(i)
            if proc is not None:
                x = proc.pre_process(x)
                mask = proc.propagate_mask(mask)
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            kwargs = {}
            if isinstance(layer, BaseRecurrentLayer) and rnn_init:
                kwargs["initial_state"] = rnn_init.get(name)
            # device-time attribution (obs/devtime.py): the scope is
            # trace-time HLO metadata only — the compiled step is
            # byte-identical; jax carries it into the backward ops as
            # transpose(jvp(<scope>)), so gradients attribute too
            lscope = obs.devtime.scope(f"{name}.{type(layer).__name__}")
            if (pre_output_last and i == n - 1
                    and isinstance(layer, (OutputLayer,))):
                # pre-activation logits for fused loss
                with lscope:
                    z = x.reshape(x.shape[0], -1) if (
                        not isinstance(layer, RnnOutputLayer)
                        and x.ndim > 2
                    ) else x
                    z = z @ params[name]["W"]
                    if layer.has_bias:
                        z = z + params[name]["b"]
                x = z
                new_state[name] = state.get(name, {})
                if stats_out is not None:
                    stats_out[name] = obs.numerics.act_summary(x)
                continue
            with lscope:
                x, s = layer.apply(params.get(name, {}),
                                   state.get(name, {}),
                                   x, train=train, rng=sub, mask=mask,
                                   **kwargs)
            if isinstance(layer, BaseRecurrentLayer):
                rnn_states[name] = s
                new_state[name] = state.get(name, {})
            else:
                new_state[name] = s
            if stats_out is not None:
                # diagnostic step: tap this layer's output AS TRACED —
                # scalars become aux outputs of the same XLA program
                stats_out[name] = obs.numerics.act_summary(x)
            mask = layer.propagate_mask(mask, None)
        return x, new_state, rnn_states

    # ------------------------------------------------------------------
    # loss
    # ------------------------------------------------------------------
    def _last_loss(self):
        last = self.layers[-1]
        if hasattr(last, "compute_loss_fn"):
            # layer-defined loss (e.g. Yolo2OutputLayer) — never fused
            return last.compute_loss_fn(), False
        loss_name = getattr(last, "loss", None)
        if loss_name is None:
            raise ValueError("last layer has no loss; use an OutputLayer/"
                             "LossLayer variant for fit()")
        act = (last.activation or "identity").lower()
        # the fused pre-activation shortcut in _forward only handles
        # OutputLayer — a LossLayer applies its activation in-layer
        fused = (act, loss_name.lower()) in _FUSABLE and \
            isinstance(last, OutputLayer)
        return loss_name, fused

    def _reg_score(self, params):
        total = 0.0
        for i, layer in enumerate(self.layers):
            l1v, l2v = layer.l1, layer.l2
            if not l1v and not l2v:
                continue
            for leaf in jax.tree.leaves(params[_lname(i)]):
                if l1v:
                    total = total + l1v * jnp.sum(jnp.abs(leaf))
                if l2v:
                    total = total + 0.5 * l2v * jnp.sum(jnp.square(leaf))
        return total

    def _apply_weight_noise(self, params, rng):
        """Train-time weight noise per layer (reference WeightNoise /
        DropConnect, conf.weightnoise) — perturbs the forward's view of
        the params; the master params are untouched."""
        out = dict(params)
        for i, layer in enumerate(self.layers):
            wn = getattr(layer, "weight_noise", None)
            if wn is not None and _lname(i) in out:
                rng, sub = jax.random.split(rng)
                out[_lname(i)] = wn.apply(out[_lname(i)], sub)
        return out

    def _apply_constraints(self, params):
        """Post-update parameter constraints per layer (reference
        LayerConstraint, applied after the updater step)."""
        out = dict(params)
        for i, layer in enumerate(self.layers):
            cs = getattr(layer, "constraints", None)
            if cs and _lname(i) in out:
                p = out[_lname(i)]
                for c in cs:
                    p = c.apply(p)
                out[_lname(i)] = p
        return out

    def _loss_fn(self, params, state, x, y, mask, lmask, rng,
                 act_stats=None):
        loss_name, fused = self._last_loss()
        cd = self.conf.compute_dtype
        master = params
        if any(getattr(l, "weight_noise", None) is not None
               for l in self.layers):
            nrng, rng = jax.random.split(rng)
            params = self._apply_weight_noise(params, nrng)
        if cd is not None:
            # bf16 fwd/bwd, fp32 master params: the cast is inside the
            # grad trace, so grads come back fp32 for the optimizer
            params = dtypes.cast_float_tree(params, cd)
            x = dtypes.cast_float_tree(x, cd)
        out, new_state, _ = self._forward(
            params, state, x, train=True, rng=rng, mask=mask,
            pre_output_last=fused, stats_out=act_stats)
        loss_fn = losses_mod.get(loss_name)
        # devtime scope: names the loss+regularization device share
        with obs.devtime.scope(f"loss.{loss_name}"):
            if cd is not None and losses_mod.wants_f32_logits(loss_fn,
                                                              fused):
                out = out.astype(jnp.float32)
            kw = {"from_logits": True} if fused else {}
            data_loss = loss_fn(y, out, mask=lmask, **kw)
            total = data_loss + self._reg_score(master)
        return total, new_state

    # ------------------------------------------------------------------
    # fit
    # ------------------------------------------------------------------
    def _update(self, params, opt_state, state, x, y, mask, lmask, rng):
        """One gradient+optimizer update — the single source of truth
        traced by both the per-batch step and the scanned loop."""
        (loss, new_state), grads = jax.value_and_grad(
            self._loss_fn, has_aux=True)(
                params, state, x, y, mask, lmask, rng)
        # devtime scope: names the optimizer's device share next to
        # the per-layer forward/backward scopes
        with obs.devtime.scope("optimizer.update"):
            updates, opt_state = self._optimizer.update(grads,
                                                        opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            params = self._apply_constraints(params)
        return params, opt_state, new_state, loss

    def _make_train_step(self):
        return sentry.jit(self._update,
                          name="MultiLayerNetwork.train_step",
                          donate_argnums=(0, 1, 2))

    # ------------------------------------------------------------------
    # numerics observatory (obs/numerics.py — ARCHITECTURE.md §11)
    # ------------------------------------------------------------------
    def _layer_names(self):
        return [_lname(i) for i in range(len(self.layers))]

    def monitor_numerics(self, every: int = 1,
                         histograms: bool = False,
                         raise_on_nonfinite: bool = True):
        """Attach the numerics observatory: every ``every``-th step is
        a *diagnostic step* — a second compiled variant of the train
        step whose aux outputs are per-layer gradient/update/param
        norms, activation stats from the real training forward, and
        the non-finite sentinel (see ``obs/numerics.py``). Off the
        cadence, the default step runs untouched."""
        self._numerics = obs.numerics.NumericsMonitor(
            every=every, histograms=histograms,
            raise_on_nonfinite=raise_on_nonfinite)
        self._diag_step_fn = None   # config is traced into the program
        return self

    def _make_diag_step(self):
        histograms = self._numerics.histograms \
            if self._numerics is not None else False
        layers = self._layer_names()

        def diag_update(params, opt_state, state, x, y, mask, lmask,
                        rng):
            def lf(p):
                stats = {}
                loss, new_state = self._loss_fn(
                    p, state, x, y, mask, lmask, rng, act_stats=stats)
                return loss, (new_state, stats)

            (loss, (new_state, act_stats)), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            updates, new_opt = self._optimizer.update(grads, opt_state,
                                                      params)
            new_params = optax.apply_updates(params, updates)
            new_params = self._apply_constraints(new_params)
            diag = obs.numerics.build_diag(
                new_params, grads, updates, act_stats, layers,
                histograms=histograms)
            # packed: 2 host transfers per diag step instead of ~10
            return (new_params, new_opt, new_state, loss,
                    obs.numerics.pack_diag(diag))

        return sentry.jit(diag_update,
                          name="MultiLayerNetwork.diag_step",
                          donate_argnums=(0, 1, 2))

    def _fit_batch_diag(self, x, y, fmask, lmask, t0):
        """Cadence-gated diagnostic step: same update, plus the
        numerics aux outputs (scalars-only host pull at cadence)."""
        if self._diag_step_fn is None:
            self._diag_step_fn = self._make_diag_step()
        rng = jax.random.fold_in(jax.random.PRNGKey(self.conf.seed),
                                 self.iteration)
        t1 = obs.now()
        try:
            self.params, self.opt_state, self.state, loss, diag = \
                self._diag_step_fn(self.params, self.opt_state,
                                   self.state, x, y, fmask, lmask, rng)
            t2 = obs.now()
            self.score_ = float(loss)   # blocking device sync
        except Exception as e:       # HBM OOM → diagnostic dump
            from deeplearning4j_tpu.utils import crashreport
            if crashreport.is_oom(e):
                path = crashreport.write_memory_crash_dump(self, e)
                if path:
                    raise RuntimeError(
                        f"diagnostic training step ran out of device "
                        f"memory (the numerics aux outputs keep "
                        f"grads+updates alive together — try a "
                        f"sparser cadence); crash dump written to "
                        f"{path}") from e
            raise
        obs.record_step("MultiLayerNetwork.fit", t0, t1, t2, obs.now())
        self.iteration += 1
        # publishes gauges/trace counters and raises NonFiniteError
        # naming the origin layer when the sentinel fired
        self._numerics.process(self, diag, self._layer_names(),
                               entry="MultiLayerNetwork")
        tl0 = obs.now()
        for l in self.listeners:
            l.iteration_done(self, self.iteration, self.epoch)
        if self.listeners and obs.trace.enabled():
            obs.trace.add_span("MultiLayerNetwork.fit/listeners",
                               tl0, obs.now())

    def _make_train_loop(self):
        """K train steps per dispatched executable (``lax.scan`` over
        stacked batches) — see ComputationGraph._make_train_loop.
        Numerically identical to K sequential ``fit`` calls (same
        per-iteration rng fold_in scheme)."""
        def one(carry, batch):
            params, opt_state, state = carry
            x, y, rng = batch
            params, opt_state, new_state, loss = self._update(
                params, opt_state, state, x, y, None, None, rng)
            return (params, opt_state, new_state), loss

        def loop(params, opt_state, state, x_stack, y_stack, rng_stack):
            (p, o, s), losses = jax.lax.scan(
                one, (params, opt_state, state),
                (x_stack, y_stack, rng_stack))
            return p, o, s, losses

        return sentry.jit(loop, name="MultiLayerNetwork.train_loop",
                          donate_argnums=(0, 1, 2))

    def _refresh_ambient_trace(self):
        """Nets whose layers consult the ambient distributed context
        (``sequence_parallel`` attention) bake that decision into their
        jitted traces — drop the caches whenever the context has
        changed since tracing, so entering/exiting
        ``parallel.distributed_context`` never runs a stale plan."""
        if not any(getattr(l, "sequence_parallel", None)
                   for l in self.layers):
            return
        from deeplearning4j_tpu.parallel.mesh import context_epoch
        e = context_epoch()
        if getattr(self, "_ctx_epoch", None) != e:
            self._ctx_epoch = e
            self._train_step_fn = None
            self._train_loop_fn = None
            self._output_fn = None
            self._diag_step_fn = None

    def _fit_group(self, group):
        nm = self._numerics
        if nm is not None and any(nm.due(self.iteration + i)
                                  for i in range(len(group))):
            # a diagnostic step is due inside this group: the scanned
            # loop has no per-step aux outputs, so run the group's
            # batches individually (the cadence path, not the hot one)
            nm.note_group_split(len(group))
            for x, y in group:
                self._fit_batch(x, y)
            return
        t0 = obs.now()
        faults.inject("step")       # site: step dispatch (resilience/)
        self._refresh_ambient_trace()
        if self._train_loop_fn is None:
            self._train_loop_fn = self._make_train_loop()
        obs.devtime.step_started(self.iteration)
        obs.commtime.step_started(self.iteration)
        xs = jnp.stack([jnp.asarray(np.asarray(x)) for x, _ in group])
        ys = jnp.stack([jnp.asarray(np.asarray(y)) for _, y in group])
        base = jax.random.PRNGKey(self.conf.seed)
        rngs = jnp.stack([jax.random.fold_in(base, self.iteration + i)
                          for i in range(len(group))])
        t1 = obs.now()
        try:
            self.params, self.opt_state, self.state, losses = \
                self._train_loop_fn(self.params, self.opt_state,
                                    self.state, xs, ys, rngs)
        except Exception as e:       # HBM OOM → diagnostic dump
            from deeplearning4j_tpu.utils import crashreport
            if crashreport.is_oom(e):
                path = crashreport.write_memory_crash_dump(self, e)
                if path:
                    raise RuntimeError(
                        f"scanned train loop ran out of device memory "
                        f"(steps_per_loop={len(group)} stacks the group "
                        f"on device — try a smaller value); crash dump "
                        f"written to {path}") from e
            raise
        t2 = obs.now()
        losses = np.asarray(losses)   # blocking device sync
        t3 = obs.now()
        obs.devtime.step_ended(self._train_loop_fn)
        obs.commtime.step_ended(self._train_loop_fn)
        obs.record_step("MultiLayerNetwork.fit", t0, t1, t2, t3,
                        args={"steps": len(group)})
        tl0 = obs.now()
        for loss in losses:
            self.score_ = float(loss)
            self.iteration += 1
            for l in self.listeners:
                l.iteration_done(self, self.iteration, self.epoch)
        if nm is not None:
            nm.note_score(self.score_)
        if self.listeners and obs.trace.enabled():
            obs.trace.add_span("MultiLayerNetwork.fit/listeners",
                               tl0, obs.now())

    def _flush_group(self, group):
        if not group:
            return
        if len(group) == 1:
            self._fit_batch(*group[0])
        else:
            self._fit_group(list(group))
        group.clear()

    def fit(self, features, labels=None, *, epochs: int = 1,
            features_mask=None, labels_mask=None, steps_per_loop: int = 1):
        """fit(x, y) for one batch, or fit(iterator, epochs=N).

        Iterator elements: DataSet-like (``.features``/``.labels``/
        ``.features_mask``/``.labels_mask``) or (x, y) tuples.
        Reference: MultiLayerNetwork.fit(DataSetIterator) — SURVEY §3.2.
        ``steps_per_loop``: batches are grouped and run K steps per
        dispatched executable (scanned device loop) — amortises
        host/dispatch latency; mask-free uniformly-shaped batches only.
        """
        if labels is not None:
            self._fit_batch(features, labels, features_mask, labels_mask)
            return self
        if hasattr(features, "features") and not hasattr(features,
                                                         "__iter__"):
            ds = features           # fit(DataSet) — reference API
            self._fit_batch(ds.features, ds.labels,
                            getattr(ds, "features_mask", None),
                            getattr(ds, "labels_mask", None))
            return self
        it = features
        for _ in range(epochs):
            for l in self.listeners:
                l.on_epoch_start(self)
            if hasattr(it, "reset"):
                it.reset()
            group: list = []
            src = iter(it)
            while True:
                te0 = obs.now()     # iterator wait = ETL attribution
                try:
                    ds = next(src)
                except StopIteration:
                    break
                obs.record_etl("MultiLayerNetwork.fit", te0, obs.now())
                if hasattr(ds, "features"):
                    x, y = ds.features, ds.labels
                    fm = getattr(ds, "features_mask", None)
                    lm = getattr(ds, "labels_mask", None)
                else:
                    x, y = ds
                    fm = lm = None
                tbptt = (self.conf.backprop_type == "TruncatedBPTT"
                         and np.ndim(x) == 3)
                if steps_per_loop > 1 and fm is None and lm is None \
                        and not tbptt:
                    if group and (np.shape(group[-1][0]) != np.shape(x)
                                  or np.shape(group[-1][1]) != np.shape(y)):
                        self._flush_group(group)
                    group.append((x, y))
                    if len(group) == steps_per_loop:
                        self._flush_group(group)
                else:
                    self._flush_group(group)
                    self._fit_batch(x, y, fm, lm)
            self._flush_group(group)
            for l in self.listeners:
                l.on_epoch_end(self)
            self.epoch += 1
        return self

    def _fit_batch(self, x, y, fmask=None, lmask=None):
        t0 = obs.now()
        faults.inject("step")       # site: step dispatch (resilience/)
        x = jnp.asarray(np.asarray(x))
        y = jnp.asarray(np.asarray(y))
        if (self.conf.backprop_type == "TruncatedBPTT" and x.ndim == 3):
            return self._fit_tbptt(x, y, fmask, lmask, _t0=t0)
        self._refresh_ambient_trace()
        nm = self._numerics     # off path: one attribute check
        if nm is not None and nm.due(self.iteration):
            return self._fit_batch_diag(x, y, fmask, lmask, t0)
        if self._train_step_fn is None:
            self._train_step_fn = self._make_train_step()
        # devtime + commtime capture windows (obs/devtime.py,
        # obs/commtime.py): off path is one module-global branch
        # inside each hook
        obs.devtime.step_started(self.iteration)
        obs.commtime.step_started(self.iteration)
        rng = jax.random.fold_in(jax.random.PRNGKey(self.conf.seed),
                                 self.iteration)
        t1 = obs.now()
        try:
            self.params, self.opt_state, self.state, loss = \
                self._train_step_fn(self.params, self.opt_state,
                                    self.state, x, y, fmask, lmask, rng)
            t2 = obs.now()
            self.score_ = float(loss)   # blocking device sync
            obs.devtime.step_ended(self._train_step_fn)
            obs.commtime.step_ended(self._train_step_fn)
        except Exception as e:       # HBM OOM → diagnostic dump
            from deeplearning4j_tpu.utils import crashreport
            if crashreport.is_oom(e):
                path = crashreport.write_memory_crash_dump(self, e)
                if path:
                    raise RuntimeError(
                        f"training step ran out of device memory; "
                        f"crash dump written to {path}") from e
            raise
        obs.record_step("MultiLayerNetwork.fit", t0, t1, t2, obs.now())
        self.iteration += 1
        if nm is not None:
            nm.note_score(self.score_)
        tl0 = obs.now()
        for l in self.listeners:
            l.iteration_done(self, self.iteration, self.epoch)
        if self.listeners and obs.trace.enabled():
            obs.trace.add_span("MultiLayerNetwork.fit/listeners",
                               tl0, obs.now())

    # -- truncated BPTT (reference: fit segments of tbpttLength, carrying
    #    rnn state across segments; MultiLayerNetwork truncated-BPTT path)
    def _fit_tbptt(self, x, y, fmask, lmask, _t0=None):
        t0 = obs.now() if _t0 is None else _t0
        t1 = obs.now()
        k = self.conf.tbptt_fwd_length
        t = x.shape[1]
        rnn_states = None
        if self._tbptt_step_fn_ is None:
            self._tbptt_step_fn_ = self._make_tbptt_step()
        rng = jax.random.fold_in(jax.random.PRNGKey(self.conf.seed),
                                 self.iteration)
        scannable = (t % k == 0 and t // k > 1 and y.ndim == 3
                     and fmask is None and lmask is None)
        if scannable:
            # segment 0 with the plain step (also yields the rnn-state
            # pytree structure), remaining segments in ONE scanned
            # executable — a T=200/k=50 batch costs 2 dispatches, not 4
            (self.params, self.opt_state, self.state, rnn_states,
             loss) = self._tbptt_step_fn_(
                self.params, self.opt_state, self.state, None,
                x[:, :k], y[:, :k], None, None, rng)
            if self._tbptt_loop_fn_ is None:
                step_fn = self._tbptt_step_fn_

                def seg(carry, batch):
                    params, opt_state, state, rnn, key = carry
                    xs, ys = batch
                    params, opt_state, state, rnn, loss = step_fn(
                        params, opt_state, state, rnn, xs, ys, None,
                        None, key)
                    return (params, opt_state, state, rnn, key), loss

                def loop(params, opt_state, state, rnn, xstack, ystack,
                         key):
                    (p, o, s, r, _), losses = jax.lax.scan(
                        seg, (params, opt_state, state, rnn, key),
                        (xstack, ystack))
                    return p, o, s, r, losses[-1]
                self._tbptt_loop_fn_ = sentry.jit(
                    loop, name="MultiLayerNetwork.tbptt_loop",
                    donate_argnums=(0, 1, 2))
            n_seg = t // k - 1
            xstack = jnp.swapaxes(
                x[:, k:].reshape(x.shape[0], n_seg, k, *x.shape[2:]),
                0, 1)
            ystack = jnp.swapaxes(
                y[:, k:].reshape(y.shape[0], n_seg, k, *y.shape[2:]),
                0, 1)
            (self.params, self.opt_state, self.state, rnn_states,
             loss) = self._tbptt_loop_fn_(
                self.params, self.opt_state, self.state, rnn_states,
                xstack, ystack, rng)
        else:
            loss = None
            for s0 in range(0, t, k):
                xs = x[:, s0:s0 + k]
                ys = y[:, s0:s0 + k] if y.ndim == 3 else y
                fs = fmask[:, s0:s0 + k] if fmask is not None else None
                ls = lmask[:, s0:s0 + k] if lmask is not None else None
                (self.params, self.opt_state, self.state, rnn_states,
                 loss) = self._tbptt_step_fn_(
                    self.params, self.opt_state, self.state, rnn_states,
                    xs, ys, fs, ls, rng)
                # segments stay enqueued on device (no per-segment sync)
        t2 = obs.now()
        self.score_ = float(loss)      # one device->host sync per batch
        obs.record_step("MultiLayerNetwork.fit_tbptt", t0, t1, t2,
                        obs.now())
        self.iteration += 1
        if self._numerics is not None:   # tbptt has no diag variant:
            self._numerics.note_score(self.score_)   # escalation only
        tl0 = obs.now()
        for l in self.listeners:
            l.iteration_done(self, self.iteration, self.epoch)
        if self.listeners and obs.trace.enabled():
            obs.trace.add_span("MultiLayerNetwork.fit/listeners",
                               tl0, obs.now())

    _tbptt_step_fn_ = None
    _tbptt_loop_fn_ = None

    def _make_tbptt_step(self):
        optimizer = self._optimizer
        loss_name, fused = self._last_loss()
        loss_fn = losses_mod.get(loss_name)

        cd = self.conf.compute_dtype

        def loss_with_state(params, state, rnn_init, x, y, mask, lmask,
                            rng):
            master = params
            if any(getattr(l, "weight_noise", None) is not None
                   for l in self.layers):
                nrng, rng = jax.random.split(rng)
                params = self._apply_weight_noise(params, nrng)
            if cd is not None:
                params = dtypes.cast_float_tree(params, cd)
                x = dtypes.cast_float_tree(x, cd)
            out, new_state, rnn_states = self._forward(
                params, state, x, train=True, rng=rng, mask=mask,
                rnn_init=rnn_init, pre_output_last=fused)
            if cd is not None and losses_mod.wants_f32_logits(loss_fn,
                                                              fused):
                out = out.astype(jnp.float32)
            kw = {"from_logits": True} if fused else {}
            loss = loss_fn(y, out, mask=lmask, **kw)
            return loss + self._reg_score(master), (new_state, rnn_states)

        def step(params, opt_state, state, rnn_init, x, y, mask, lmask,
                 rng):
            (loss, (new_state, rnn_states)), grads = jax.value_and_grad(
                loss_with_state, has_aux=True)(
                    params, state, rnn_init, x, y, mask, lmask, rng)
            # stop state gradients across segment boundary (truncation)
            rnn_states = jax.tree.map(jax.lax.stop_gradient, rnn_states)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            params = self._apply_constraints(params)
            return params, opt_state, new_state, rnn_states, loss

        return sentry.jit(step, name="MultiLayerNetwork.tbptt_step")

    # ------------------------------------------------------------------
    # inference
    # ------------------------------------------------------------------
    def _make_output_fn(self):
        cd = self.conf.compute_dtype

        def infer(params, state, x, mask):
            if cd is not None:
                params = dtypes.cast_float_tree(params, cd)
                state = dtypes.cast_float_tree(state, cd)
                x = dtypes.cast_float_tree(x, cd)
            out, _, _ = self._forward(params, state, x, train=False,
                                      rng=None, mask=mask)
            return out.astype(jnp.float32) if cd is not None else out

        return sentry.jit(infer, name="MultiLayerNetwork.output")

    def output(self, x, train: bool = False, mask=None):
        """Reference: MultiLayerNetwork.output (SURVEY §3.3)."""
        x = jnp.asarray(np.asarray(x))
        self._refresh_ambient_trace()
        if self._output_fn is None:
            self._output_fn = self._make_output_fn()
        return self._output_fn(self.params, self.state, x, mask)

    def warmup(self, specs):
        """AOT-compile the train step, scanned loop, and output fn for
        every declared shape bucket BEFORE the first batch/request (see
        ``perf.warmup``): ``.lower().compile()`` from abstract shapes —
        no real data, no device stall at first use. Returns
        ``{"compiled": n, "seconds": t}``."""
        from deeplearning4j_tpu.perf.warmup import warmup_network
        self._refresh_ambient_trace()
        return warmup_network(self, specs)

    def feed_forward(self, x, train: bool = False):
        """All layer activations (reference feedForward): list, input
        first."""
        x = jnp.asarray(np.asarray(x))
        params = self._materialize_ties(self.params)
        acts = [x]
        cur = x
        for i, layer in enumerate(self.layers):
            proc = self.conf.input_preprocessors.get(i)
            if proc is not None:
                cur = proc.pre_process(cur)
            cur, _ = layer.apply(params[_lname(i)],
                                 self.state[_lname(i)], cur,
                                 train=train, rng=None)
            acts.append(cur)
        return acts

    def activate_selected_layers(self, from_: int, to: int, x):
        cur = jnp.asarray(np.asarray(x))
        params = self._materialize_ties(self.params)
        for i in range(from_, to + 1):
            proc = self.conf.input_preprocessors.get(i)
            if proc is not None:
                cur = proc.pre_process(cur)
            cur, _ = self.layers[i].apply(
                params[_lname(i)], self.state[_lname(i)], cur,
                train=False, rng=None)
        return cur

    def rnn_clear_previous_state(self):
        self._rnn_state = None

    def rnn_time_step(self, x, mask=None):
        """Stateful single/multi-step inference (reference rnnTimeStep):
        carries recurrent state between calls."""
        x = jnp.asarray(np.asarray(x))
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]
        out, _, rnn_states = self._forward(
            self.params, self.state, x, train=False, rng=None, mask=mask,
            rnn_init=self._rnn_state)
        self._rnn_state = rnn_states
        if squeeze and out.ndim == 3:
            out = out[:, -1]
        return out

    # ------------------------------------------------------------------
    # scoring / evaluation
    # ------------------------------------------------------------------
    def score(self, dataset=None) -> float:
        if dataset is None:
            return self.score_
        x, y = dataset.features, dataset.labels
        loss_name, fused = self._last_loss()
        out, _, _ = self._forward(
            self.params, self.state, jnp.asarray(np.asarray(x)),
            train=False, rng=None,
            mask=getattr(dataset, "features_mask", None),
            pre_output_last=fused)
        kw = {"from_logits": True} if fused else {}
        loss = losses_mod.get(loss_name)(
            jnp.asarray(np.asarray(y)), out,
            mask=getattr(dataset, "labels_mask", None), **kw)
        return float(loss + self._reg_score(self.params))

    def evaluate(self, iterator):
        """Classification evaluation (reference MultiLayerNetwork
        .evaluate(DataSetIterator) → Evaluation)."""
        from deeplearning4j_tpu.eval_.evaluation import Evaluation
        e = Evaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            if hasattr(ds, "features"):
                x, y = ds.features, ds.labels
            else:
                x, y = ds
            out = self.output(x)
            e.eval(np.asarray(y), np.asarray(out))
        return e

    def evaluate_regression(self, iterator):
        from deeplearning4j_tpu.eval_.evaluation import RegressionEvaluation
        e = RegressionEvaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            x, y = (ds.features, ds.labels) if hasattr(ds, "features") \
                else ds
            e.eval(np.asarray(y), np.asarray(self.output(x)))
        return e

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def num_params(self) -> int:
        return sum(int(np.prod(np.shape(l)))
                   for l in jax.tree.leaves(self.params))

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    def summary(self) -> str:
        lines = ["=" * 68,
                 f"{'Layer':<30}{'Output':<20}{'Params':>10}",
                 "=" * 68]
        total = 0
        for i, layer in enumerate(self.layers):
            n = sum(int(np.prod(np.shape(l)))
                    for l in jax.tree.leaves(self.params[_lname(i)]))
            total += n
            lines.append(f"{type(layer).__name__:<30}"
                         f"{str(self._layer_shapes[i]):<20}{n:>10,}")
        lines.append("=" * 68)
        lines.append(f"Total params: {total:,}")
        return "\n".join(lines)

    def clone(self) -> "MultiLayerNetwork":
        import copy
        net = MultiLayerNetwork(copy.deepcopy(self.conf))
        net.params = jax.tree.map(lambda x: x, self.params)
        net.state = jax.tree.map(lambda x: x, self.state)
        net._input_shape = getattr(self, "_input_shape", None)
        net._build_optimizer()
        return net
