"""Weight initialization — reference: ``org.deeplearning4j.nn.weights.WeightInit``
enum + ``WeightInitUtil`` (deeplearning4j-nn).

Fan-in/fan-out conventions match the reference: XAVIER = glorot normal,
RELU = He normal, etc. All initializers take a jax PRNG key.
"""
from __future__ import annotations

import math
from typing import Callable, Dict

import jax
import jax.numpy as jnp


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [*spatial, in, out] (channels-last layout)
    receptive = math.prod(shape[:-2])
    return shape[-2] * receptive, shape[-1] * receptive


def xavier(key, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    std = math.sqrt(2.0 / (fan_in + fan_out))
    return jax.random.normal(key, shape, dtype) * std


def xavier_uniform(key, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    limit = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -limit, limit)


def xavier_fan_in(key, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)


def relu_init(key, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / fan_in)


def relu_uniform(key, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    limit = math.sqrt(6.0 / fan_in)
    return jax.random.uniform(key, shape, dtype, -limit, limit)


def lecun_normal(key, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)


def lecun_uniform(key, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    limit = math.sqrt(3.0 / fan_in)
    return jax.random.uniform(key, shape, dtype, -limit, limit)


def normal(key, shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype) / math.sqrt(shape[-1])


def uniform(key, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    a = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, -a, a)


def zero(key, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones_(key, shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def identity(key, shape, dtype=jnp.float32):
    if len(shape) != 2 or shape[0] != shape[1]:
        raise ValueError("IDENTITY init needs square 2-D shape")
    return jnp.eye(shape[0], dtype=dtype)


_REGISTRY: Dict[str, Callable] = {
    "xavier": xavier,
    "xavier_uniform": xavier_uniform,
    "xavier_fan_in": xavier_fan_in,
    "glorot_normal": xavier,
    "glorot_uniform": xavier_uniform,
    "relu": relu_init,
    "he_normal": relu_init,
    "relu_uniform": relu_uniform,
    "he_uniform": relu_uniform,
    "lecun_normal": lecun_normal,
    "lecun_uniform": lecun_uniform,
    "normal": normal,
    "uniform": uniform,
    "zero": zero,
    "ones": ones_,
    "identity": identity,
}


def get(name_or_fn) -> Callable:
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown weight init {name_or_fn!r}; known: "
                         f"{sorted(_REGISTRY)}")
    return _REGISTRY[key]
